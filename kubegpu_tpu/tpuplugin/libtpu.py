"""Real-hardware backend: enumerate chips via the JAX TPU client.

On a real TPU VM, ``jax.local_devices()`` exposes per-device ``.coords``
(global ICI mesh coordinate) and ``.process_index`` — the libtpu-backed
equivalent of the reference's NVML enumeration (SURVEY.md §3
``NvidiaGPUManager``).  Three discovery modes, most-informed first:

1. **Registry slice** — when ``TPU_ACCELERATOR_TYPE`` (set on Cloud TPU
   VMs / injected by the crishim) names a known topology
   (``v5litepod-16`` → ``v5e-16``), the advertisement describes this
   host as ONE HOST OF THAT SLICE: global mesh shape/wrap/host_block
   from the registry, ``host_id`` from ``TPU_WORKER_ID`` (fallback:
   ``process_index``), and the local chips' coords VALIDATED against
   the host_block tiling for that host id — a mismatched worker id
   would silently corrupt TPU_WORKER_ID ordering downstream (SURVEY.md
   §8 "Worker identity wiring"), so it raises instead.  N hosts
   advertising this way assemble into the full slice via
   ``SliceState.from_advertisements``.
2. **Local standalone** — no recognized type: the host's chips form
   their own slice (coords normalized to origin), which is exactly what
   the single-chip axon tunnel and CPU test environments look like.
3. Raise when no TPU devices are visible at all.

Health is first-class (the reference's NVML path reported per-device
health; SURVEY.md §6 failure-detection row): a pluggable
``health_check`` callable vets each chip at discovery time, and
``mark_chip_unhealthy`` / ``report_bad_link`` let node-local monitors
(ECC scrubbers, link flap counters) feed faults into the next
advertisement tick.
"""

from __future__ import annotations

import os
import re

from kubegpu_tpu.topology.mesh import TOPOLOGY_REGISTRY, TopologySpec, TpuTopology
from kubegpu_tpu.tpuplugin.backend import (
    MILLICHIPS_PER_CHIP,
    ChipAdvertisement,
    DeviceBackend,
    NodeAdvertisement,
)
from kubegpu_tpu.tpuplugin.mock import build_tpu_env

_DEFAULT_HBM_GIB = 16.0

# Cloud TPU accelerator-type strings → registry slice types.
_ACCEL_RE = re.compile(r"^(v\d+[a-z]*(?:litepod|pod)?)-(\d+)$")
_GEN_MAP = {"v5litepod": "v5e", "v5e": "v5e", "v4": "v4",
            "v5p": "v5p", "v5pod": "v5p"}


def slice_type_from_accelerator(accel_type: str | None) -> str | None:
    """``TPU_ACCELERATOR_TYPE`` → registry key, or None when unknown.

    v4/v5p accelerator-type counts are TensorCores (2/chip); v5e counts
    are chips.  The registry names follow the same convention
    (``v4-8`` = 4 chips), so the count passes through unchanged.
    """
    if not accel_type:
        return None
    m = _ACCEL_RE.match(accel_type.strip())
    if not m:
        return None
    gen = _GEN_MAP.get(m.group(1))
    if gen is None:
        return None
    name = f"{gen}-{m.group(2)}"
    return name if name in TOPOLOGY_REGISTRY else None


class LibtpuBackend(DeviceBackend):
    """Discover this host's real TPU chips through JAX."""

    def __init__(self, slice_id: str | None = None,
                 node_name: str | None = None,
                 health_check=None):
        self.slice_id = slice_id or os.environ.get(
            "KUBETPU_SLICE_ID", "local-slice")
        self.node_name = node_name or os.environ.get("HOSTNAME", "local-node")
        # health_check(local_chip_index, device) -> bool; None = healthy
        self.health_check = health_check
        self.unhealthy_chips: set[int] = set()
        self.bad_links: set[tuple] = set()  # normalized coord pairs

    # -- fault hooks (node-local monitors feed these; the advertiser's
    #    next tick picks them up, mirroring MockBackend's test hooks) ----

    def mark_chip_unhealthy(self, local_index: int) -> None:
        self.unhealthy_chips.add(local_index)

    def heal_chip(self, local_index: int) -> None:
        self.unhealthy_chips.discard(local_index)

    def report_bad_link(self, a, b) -> None:
        a, b = tuple(a), tuple(b)
        self.bad_links.add((min(a, b), max(a, b)))

    def heal_link(self, a, b) -> None:
        a, b = tuple(a), tuple(b)
        self.bad_links.discard((min(a, b), max(a, b)))

    # -- discovery -------------------------------------------------------

    @staticmethod
    def _local_chips(tpus) -> list[tuple[tuple, object]]:
        """Deduplicated (3D coord, device) per PHYSICAL CHIP, in device
        order.  Megacore generations expose 2 cores per chip sharing one
        coord — TPU_VISIBLE_CHIPS indexes chips, not cores, so the
        local_index MUST count deduped chips."""
        out: list[tuple[tuple, object]] = []
        seen: set[tuple] = set()
        for li, d in enumerate(tpus):
            coord = tuple(getattr(d, "coords", (li, 0, 0)))
            if len(coord) == 2:          # 2D generations: z = 0
                coord = (coord[0], coord[1], 0)
            if coord in seen:
                continue
            seen.add(coord)
            out.append((coord, d))
        return out

    @staticmethod
    def _hbm_gib(device) -> float:
        try:
            stats = device.memory_stats()
            if stats and "bytes_limit" in stats:
                return stats["bytes_limit"] / (1 << 30)
        except Exception:
            pass
        return _DEFAULT_HBM_GIB

    def discover(self) -> NodeAdvertisement:
        import jax  # deferred: control-plane processes must not init TPU

        local = jax.local_devices()
        tpus = [d for d in local if d.platform.startswith(("tpu", "axon"))]
        if not tpus:
            raise RuntimeError("LibtpuBackend: no TPU devices visible")
        chip_devs = self._local_chips(tpus)
        spec = self._registry_spec()
        if spec is not None:
            return self._discover_registry(spec, chip_devs, tpus)
        return self._discover_local(chip_devs, tpus)

    @staticmethod
    def _registry_spec() -> TopologySpec | None:
        name = slice_type_from_accelerator(
            os.environ.get("TPU_ACCELERATOR_TYPE"))
        return TOPOLOGY_REGISTRY.get(name) if name else None

    def _discover_registry(self, spec: TopologySpec, chip_devs,
                           tpus) -> NodeAdvertisement:
        """One host of a known multi-host slice: validate this host's
        chips against the host_block tiling for its worker id."""
        topo = TpuTopology.build(spec)
        host_id = int(os.environ.get(
            "TPU_WORKER_ID", getattr(tpus[0], "process_index", 0)))
        if not 0 <= host_id < spec.num_hosts:
            raise ValueError(
                f"LibtpuBackend: worker id {host_id} out of range for "
                f"{spec.name} ({spec.num_hosts} hosts)")
        expected = {topo.chips[i].coord
                    for i in topo.hosts[host_id].chip_indices}
        got = {c for c, _ in chip_devs}
        if got != expected:
            raise ValueError(
                f"LibtpuBackend: host {host_id} of {spec.name} should own "
                f"chips {sorted(expected)} per the host_block tiling, but "
                f"jax reports {sorted(got)} — a mismatched TPU_WORKER_ID "
                "here would corrupt worker ordering, refusing to "
                "advertise")
        # every host of the slice must advertise the SAME slice_id for
        # SliceState.from_advertisements to assemble them; operators set
        # KUBETPU_SLICE_ID, and the default derives from the slice type
        # so same-typed hosts agree without configuration
        slice_id = (self.slice_id if self.slice_id != "local-slice"
                    else f"{spec.name}-slice")
        return self._advertisement(
            slice_type=spec.name,
            host_id=host_id,
            mesh_shape=spec.mesh_shape,
            wrap=spec.wrap,
            host_block=spec.host_block,
            chip_devs=chip_devs,
            slice_id=slice_id)

    def _discover_local(self, chip_devs, tpus) -> NodeAdvertisement:
        """Standalone single-host slice (axon tunnel, dev VM): the local
        chips ARE the mesh, coords normalized to origin."""
        coords = [c for c, _ in chip_devs]
        mins = tuple(min(c[i] for c in coords) for i in range(3))
        chip_devs = [(tuple(c[i] - mins[i] for i in range(3)), d)
                     for c, d in chip_devs]
        shape = tuple(max(c[i] for c, _ in chip_devs) + 1 for i in range(3))
        return self._advertisement(
            slice_type=f"local-{len(chip_devs)}chip",
            host_id=int(getattr(tpus[0], "process_index", 0)),
            mesh_shape=shape,
            wrap=(False, False, False),
            host_block=shape,
            chip_devs=chip_devs,
            slice_id=self.slice_id)

    def _advertisement(self, slice_type, host_id, mesh_shape, wrap,
                       host_block, chip_devs, slice_id) -> NodeAdvertisement:
        local_coords = set()
        chips = []
        for li, (coord, dev) in enumerate(chip_devs):
            healthy = li not in self.unhealthy_chips
            if healthy and self.health_check is not None:
                healthy = bool(self.health_check(li, dev))
            local_coords.add(coord)
            chips.append(ChipAdvertisement(
                coord=coord, local_index=li,
                millichips=MILLICHIPS_PER_CHIP,
                hbm_gib=self._hbm_gib(dev),
                healthy=healthy))
        # advertise only links incident to a local chip (each host owns
        # its own faults; the scheduler unions per slice)
        incident = tuple(sorted(
            (a, b) for a, b in self.bad_links
            if a in local_coords or b in local_coords))
        return NodeAdvertisement(
            node_name=self.node_name,
            slice_id=slice_id,
            slice_type=slice_type,
            host_id=host_id,
            mesh_shape=tuple(mesh_shape),
            wrap=tuple(wrap),
            host_block=tuple(host_block),
            chips=tuple(chips),
            bad_links=incident,
        )

    def allocate_env(self, chips, worker_id, num_workers,
                     coordinator_address, worker_hostnames):
        adv = self.discover()
        return build_tpu_env(adv.host_block, chips, worker_id, num_workers,
                             coordinator_address, worker_hostnames)

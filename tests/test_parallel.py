"""Mesh/sharding helpers + ring attention on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from kubegpu_tpu.ops import xla_attention
from kubegpu_tpu.parallel import make_mesh, mesh_axis_sizes
from kubegpu_tpu.parallel.ringattention import make_sharded_ring_attention
from kubegpu_tpu.parallel.sharding import fit_spec, named_sharding_tree


class TestMesh:
    def test_make_mesh_8(self):
        mesh = make_mesh({"dp": 2, "tp": 4})
        assert mesh_axis_sizes(mesh) == {"dp": 2, "tp": 4}

    def test_make_mesh_wrong_product(self):
        with pytest.raises(ValueError):
            make_mesh({"dp": 3})

    def test_fit_spec_drops_unknown_axes(self):
        mesh = make_mesh({"dp": 8})
        assert fit_spec(mesh, P("fsdp", "tp")) == P(None, None)
        assert fit_spec(mesh, P(("dp", "fsdp"), None)) == P(("dp",), None)

    def test_named_sharding_tree(self):
        mesh = make_mesh({"dp": 8})
        tree = {"a": P("dp", None), "b": {"c": P("tp")}}
        out = named_sharding_tree(mesh, tree)
        assert out["a"].spec == P("dp", None)
        assert out["b"]["c"].spec == P(None)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, causal):
        mesh = make_mesh({"sp": 8})
        b, h, t, d = 2, 2, 64, 16   # t sharded 8 ways → 8 tokens/device
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (b, h, t, d))
        k = jax.random.normal(kk, (b, h, t, d))
        v = jax.random.normal(kv, (b, h, t, d))
        ring = make_sharded_ring_attention(mesh, causal=causal)
        out = jax.jit(ring)(q, k, v)
        ref = xla_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_single_device_axis(self):
        mesh = make_mesh({"dp": 8, "sp": 1},
                         devices=jax.devices())
        # sp axis of size 1 degenerates to local attention
        b, h, t, d = 8, 2, 16, 8
        key = jax.random.PRNGKey(1)
        q = jax.random.normal(key, (b, h, t, d))
        ring = make_sharded_ring_attention(mesh)
        out = jax.jit(ring)(q, q, q)
        ref = xla_attention(q, q, q, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

"""Gang/slice allocator — reference: ``grpalloc`` + ``gpuschedulerplugin``.

The reference's hot loop (SURVEY.md §4.2 ``PodFitsGroupConstraints``)
matched grouped device requests against a hierarchical resource tree.  The
TPU-native equivalent: given cluster occupancy and a gang request
(N pods × chips each, optional logical mesh axes), find the best free
*contiguous sub-torus* atomically for the whole gang — all pods or none
(SURVEY.md §1 item 3) — scored by honest ICI locality + packing.

``ordering`` chooses the logical-device order (chip → worker/mesh position)
that maximizes ring locality — the seam where placement quality turns into
collective bandwidth.  A C++ core (``native``) accelerates the placement
search; ``gang`` is the reference implementation and always available.
"""

from kubegpu_tpu.allocator.gang import (
    GangAllocator,
    GangAssignment,
    GangRequest,
    PodAssignment,
    SliceState,
)
from kubegpu_tpu.allocator.ordering import best_logical_order, evaluate_order

__all__ = [
    "GangAllocator",
    "GangAssignment",
    "GangRequest",
    "PodAssignment",
    "SliceState",
    "best_logical_order",
    "evaluate_order",
]

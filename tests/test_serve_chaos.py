"""Chaos-hardened serving (ISSUE 4): seeded fault injection against
the paged continuous-batching engine and the dp pool — replica kill,
transient dispatch failure, NaN-logit poisoning, watchdog tick stalls,
deadlines, cancellation, and control-plane-driven failover.

The recovery contract under EVERY scenario: no admitted request is
lost, none completes twice, and every replayed greedy stream is
token-for-token identical to the fault-free run (replay re-conditions
on the accepted prefix, so greedy argmax continues identically)."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubegpu_tpu.models import LlamaConfig, greedy_generate, llama_init
from kubegpu_tpu.models.serve import ContinuousBatcher, DataParallelServePool
from kubegpu_tpu.obs.chaos import (
    ChaosEvent,
    ChaosInjector,
    ReplicaDeadError,
    TickStallError,
)
from kubegpu_tpu.obs.metrics import MetricsRegistry


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(max_seq_len=64)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    return cfg, params


def solo(params, prompt, n, cfg):
    out = greedy_generate(params, jnp.asarray(prompt, jnp.int32)[None],
                          n, cfg, max_len=cfg.max_seq_len)
    return [int(x) for x in np.asarray(out)[0]]


def mixed_prompts(cfg, n=5):
    return [([(i * 3 + j) % cfg.vocab_size for i in range(4 + j)],
             5 + j) for j in range(n)]


class TestEngineSelfDefense:
    """ContinuousBatcher-level recovery: quarantine, replay, retry
    bounds, watchdog, dispatch-failure retry, shed backpressure."""

    def _eng(self, params, cfg, **kw):
        kw.setdefault("n_slots", 2)
        kw.setdefault("stride", 2)
        kw.setdefault("prompt_buckets", (8, 16))
        kw.setdefault("paged", True)
        kw.setdefault("page_size", 8)
        return ContinuousBatcher(params, cfg, **kw)

    def test_dispatch_failure_retried_in_place(self, tiny):
        cfg, params = tiny
        reg = MetricsRegistry()
        eng = self._eng(params, cfg, metrics=reg, chaos=ChaosInjector(
            [ChaosEvent(tick=1, kind="fail_dispatch")]))
        p = [1, 2, 3]
        rid = eng.submit(p, 6)
        done = eng.drain()
        assert [r.rid for r in done] == [rid]
        assert done[0].tokens == solo(params, p, 6, cfg)
        assert eng.dispatch_failures == 1
        assert reg.counter("serve_dispatch_failures") == 1

    def test_nan_quarantine_replays_bit_exact(self, tiny):
        """A poisoned slot's request must be quarantined and replayed
        to the exact fault-free tokens; the NEIGHBOR slot must never
        notice (slots are independent batch rows)."""
        cfg, params = tiny
        reg = MetricsRegistry()
        eng = self._eng(params, cfg, metrics=reg, chaos=ChaosInjector(
            [ChaosEvent(tick=2, kind="nan_logits")]))
        prompts = [([(i * 3 + 1) % cfg.vocab_size for i in range(5)], 8),
                   ([(i * 5 + 2) % cfg.vocab_size for i in range(7)], 8)]
        rids = {eng.submit(p, n): (p, n) for p, n in prompts}
        seen = {}
        for r in eng.drain():
            assert r.rid not in seen, "duplicate completion"
            seen[r.rid] = r
        assert set(seen) == set(rids)
        assert eng.slots_quarantined == 1
        assert eng.requests_retried == 1
        assert reg.counter("serve_slots_quarantined") == 1
        assert reg.counter("serve_requests_retried") == 1
        for rid, (p, n) in rids.items():
            assert seen[rid].error is None
            assert seen[rid].tokens == solo(params, p, n, cfg), rid

    def test_retry_bound_fails_gracefully(self, tiny):
        """max_retries=0: the first quarantine exhausts the budget —
        the request surfaces FAILED (error set, partial tokens kept),
        and the engine keeps serving everyone else."""
        cfg, params = tiny
        eng = self._eng(params, cfg, max_retries=0, chaos=ChaosInjector(
            [ChaosEvent(tick=2, kind="nan_logits")]))
        p_a = [(i * 3 + 1) % cfg.vocab_size for i in range(5)]
        p_b = [(i * 5 + 2) % cfg.vocab_size for i in range(7)]
        ra = eng.submit(p_a, 8)
        rb = eng.submit(p_b, 8)
        done = {r.rid: r for r in eng.drain()}
        assert set(done) == {ra, rb}
        failed = [r for r in done.values() if r.error is not None]
        exact = [r for r in done.values() if r.error is None]
        assert len(failed) == 1 and "retries" in failed[0].error
        assert len(exact) == 1
        ok = {ra: (p_a, 8), rb: (p_b, 8)}[exact[0].rid]
        assert exact[0].tokens == solo(params, *ok, cfg)

    def test_kill_marks_dead_and_reraises(self, tiny):
        cfg, params = tiny
        eng = self._eng(params, cfg, chaos=ChaosInjector(
            [ChaosEvent(tick=1, kind="kill_replica")]))
        eng.submit([1, 2, 3], 6)
        with pytest.raises(ReplicaDeadError):
            eng.drain()
        assert eng.dead is not None
        # host-side request state survives for the failover harvest
        assert eng.slot_req or eng.queue
        with pytest.raises(ReplicaDeadError):
            eng.step()

    def test_watchdog_declares_stall(self, tiny):
        cfg, params = tiny
        eng = self._eng(params, cfg, tick_deadline_s=0.2,
                        chaos=ChaosInjector(
                            [ChaosEvent(tick=1, kind="stall_tick",
                                        stall_s=0.5)]))
        eng.warmup()
        eng.submit([1, 2, 3], 6)
        with pytest.raises(TickStallError):
            eng.drain()
        assert "watchdog" in eng.dead

    def test_deadline_cancels_with_partial_tokens(self, tiny):
        cfg, params = tiny
        eng = self._eng(params, cfg)
        r1 = eng.submit([1, 2, 3], 6, deadline_s=0.0)
        r2 = eng.submit([4, 5, 6], 6)
        done = {r.rid: r for r in eng.drain()}
        assert done[r1].error == "deadline exceeded"
        assert done[r2].error is None
        assert done[r2].tokens == solo(params, [4, 5, 6], 6, cfg)

    def test_cancel_api(self, tiny):
        cfg, params = tiny
        eng = self._eng(params, cfg, n_slots=1)
        r1 = eng.submit([1, 2, 3], 6)
        r2 = eng.submit([4, 5, 6], 6)   # queued behind the one slot
        eng.step()
        canceled = eng.cancel(r2, "user canceled")
        assert canceled is not None and canceled.error == "user canceled"
        done = {r.rid: r for r in eng.drain()}
        assert set(done) == {r1}
        assert done[r1].tokens == solo(params, [1, 2, 3], 6, cfg)
        assert eng.cancel(12345) is None

    def test_replay_exceeding_bucket_is_shed(self, tiny):
        """A replay whose prompt + accepted tokens exceed the largest
        bucket cannot be re-admitted: it must fail loudly (shed), not
        park at the queue front forever."""
        cfg, params = tiny
        eng = self._eng(params, cfg, prompt_buckets=(8,),
                        chaos=ChaosInjector(
                            [ChaosEvent(tick=2, kind="nan_logits")]))
        rid = eng.submit([1, 2, 3, 4, 5], 10)   # 5 + accepted > 8
        done = eng.drain()
        assert [r.rid for r in done] == [rid]
        assert done[0].error is not None and "bucket" in done[0].error
        assert eng.requests_shed == 1

    def test_drain_diagnostic_lists_stuck_work(self, tiny):
        """Satellite: an exhausted drain budget raises a diagnostic
        naming the stuck slots/requests instead of silently returning
        with work still in flight."""
        cfg, params = tiny
        eng = self._eng(params, cfg, n_slots=1)
        eng.submit([1, 2, 3], 30)
        eng.submit([4, 5, 6], 30)
        with pytest.raises(RuntimeError) as ei:
            eng.drain(max_ticks=2)
        msg = str(ei.value)
        assert "stuck work" in msg
        assert "slot 0" in msg and "rid=0" in msg
        assert "queued rid=1" in msg

    def test_spec_degrades_to_plain_engine(self, tiny):
        """Repeated zero-acceptance verify ticks (the untrained draft
        rejects everything) degrade the engine to γ=0 — which IS the
        decode-block path, so tokens stay bit-exact throughout."""
        cfg4 = LlamaConfig.tiny(n_heads=4, n_kv_heads=4, max_seq_len=64)
        params4 = llama_init(jax.random.PRNGKey(0), cfg4)
        reg = MetricsRegistry()
        eng = ContinuousBatcher(
            params4, cfg4, n_slots=2, stride=4, prompt_buckets=(8, 16),
            paged=True, page_size=8, spec_gamma=3, draft_layers=1,
            spec_degrade_after=2, metrics=reg)
        prompts = [([(i * 3 + 1) % cfg4.vocab_size for i in range(5)], 10),
                   ([(i * 5 + 2) % cfg4.vocab_size for i in range(7)], 10)]
        rids = {eng.submit(p, n): (p, n) for p, n in prompts}
        done = {r.rid: r for r in eng.drain()}
        assert eng.spec_degraded is True
        assert reg.counter("serve_spec_degraded") == 1
        for rid, (p, n) in rids.items():
            assert done[rid].tokens == solo(params4, p, n, cfg4), rid


class TestPoolFailover:
    """DataParallelServePool failover: seeded replica kills, stalls,
    retry bounds, deadlines — exactly-once, bit-exact."""

    def _pool(self, params, cfg, dp=2, **kw):
        kw.setdefault("n_slots", 2)
        kw.setdefault("stride", 2)
        kw.setdefault("prompt_buckets", (8, 16))
        kw.setdefault("page_size", 8)
        return DataParallelServePool(params, cfg, dp=dp, tp=1, **kw)

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_seeded_random_kill_exactly_once_bit_exact(self, tiny, seed):
        """THE property test the issue demands: kill a random replica
        at a random tick; after failover no request is lost, none is
        duplicated, and every token stream equals the solo run."""
        cfg, params = tiny
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        rng = np.random.default_rng(seed)
        victim = int(rng.integers(0, 2))
        tick = int(rng.integers(1, 6))
        reg = MetricsRegistry()
        pool = self._pool(params, cfg, metrics=reg, chaos={
            victim: ChaosInjector(
                [ChaosEvent(tick=tick, kind="kill_replica")])})
        prompts = mixed_prompts(cfg, n=6)
        rids = {pool.submit(p, n): (p, n) for p, n in prompts}
        seen = {}
        for r in pool.drain():
            assert r.rid not in seen, f"rid {r.rid} completed twice"
            seen[r.rid] = r
        assert set(seen) == set(rids), "request lost"
        assert pool.failovers == 1
        assert victim in pool.dead_replicas
        assert reg.counter("serve_failover_total") == 1
        assert reg.histogram("serve_replay_ms").count >= 1
        for rid, (p, n) in rids.items():
            assert seen[rid].error is None, (rid, seen[rid].error)
            assert seen[rid].tokens == solo(params, p, n, cfg), \
                (seed, rid)

    def test_stall_fails_over_via_watchdog(self, tiny):
        cfg, params = tiny
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        pool = self._pool(params, cfg, tick_deadline_s=0.25, chaos={
            1: ChaosInjector([ChaosEvent(tick=1, kind="stall_tick",
                                         stall_s=0.6)])})
        pool.warmup()   # compile outside the watchdog window
        prompts = mixed_prompts(cfg, n=5)
        rids = {pool.submit(p, n): (p, n) for p, n in prompts}
        done = {r.rid: r for r in pool.drain()}
        assert pool.failovers == 1
        assert "watchdog" in pool.dead_replicas[1]
        for rid, (p, n) in rids.items():
            assert done[rid].error is None
            assert done[rid].tokens == solo(params, p, n, cfg), rid

    def test_all_replicas_dead_fails_requests_not_hangs(self, tiny):
        cfg, params = tiny
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        pool = self._pool(params, cfg, chaos={
            0: ChaosInjector([ChaosEvent(tick=1, kind="kill_replica")]),
            1: ChaosInjector([ChaosEvent(tick=1, kind="kill_replica")])})
        rids = [pool.submit(p, n) for p, n in mixed_prompts(cfg, n=4)]
        done = {r.rid: r for r in pool.drain()}
        assert set(done) == set(rids)     # surfaced, not hung
        assert all(r.error is not None for r in done.values())
        with pytest.raises(ReplicaDeadError):
            pool.submit([1, 2, 3], 4)

    def test_failover_replay_bound(self, tiny):
        """max_replays=0: the kill's survivors fail gracefully instead
        of replaying forever."""
        cfg, params = tiny
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        pool = self._pool(params, cfg, max_replays=0, chaos={
            0: ChaosInjector([ChaosEvent(tick=1, kind="kill_replica")])})
        rids = [pool.submit(p, n) for p, n in mixed_prompts(cfg, n=6)]
        done = {r.rid: r for r in pool.drain()}
        assert set(done) == set(rids)
        assert any(r.error is not None and "failover" in r.error
                   for r in done.values())
        # replica-1 residents were untouched and finish exactly
        assert any(r.error is None for r in done.values())

    def test_pool_deadline(self, tiny):
        cfg, params = tiny
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        pool = self._pool(params, cfg)
        r1 = pool.submit([1, 2, 3], 6, deadline_s=0.0)
        r2 = pool.submit([4, 5, 6], 6)
        done = {r.rid: r for r in pool.drain()}
        assert done[r1].error == "deadline exceeded"
        assert done[r2].error is None
        assert done[r2].tokens == solo(params, [4, 5, 6], 6, cfg)


class TestControlPlaneFailover:
    """A dead serving replica flows through the EXISTING health
    controller as a gang eviction; the pool observes the eviction on
    the watch stream and fails the replica's requests over — the same
    event path training recovery rides (scheduler/health.py)."""

    def test_gang_eviction_drives_pool_failover(self, tiny):
        from kubegpu_tpu.cluster import SimCluster, tpu_pod
        from kubegpu_tpu.kubemeta import GangSpec

        cfg, params = tiny
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        cl = SimCluster(["v5e-16", "v5e-16"])
        try:
            # two serving gangs = two dp replicas in the control plane
            for g in range(2):
                cl.submit(tpu_pod(
                    f"serve{g}-0", chips=4, workload="serving",
                    gang=GangSpec(name=f"serve{g}", size=1, index=0),
                    mesh_axes={"tp": 4}, command=["noop"]))
            result, _ = cl.step()
            assert len(result.scheduled) == 2

            pool = DataParallelServePool(
                params, cfg, dp=2, tp=1, n_slots=2, stride=2,
                prompt_buckets=(8, 16), page_size=8,
                metrics=cl.metrics)
            pool.bind_replica_gang(0, "serve0")
            pool.bind_replica_gang(1, "serve1")
            pool.watch_health(cl.api)
            prompts = mixed_prompts(cfg, n=5)
            rids = {pool.submit(p, n): (p, n) for p, n in prompts}
            done = {}
            for _ in range(3):
                for r in pool.step():
                    done[r.rid] = r

            # kill the host under serving gang 0: the health controller
            # evicts the gang (delete + recreate), the DELETED events
            # hit the pool's watch, and the next step fails over
            from kubegpu_tpu.kubemeta.codec import pod_allocation
            victim = pod_allocation(cl.api.get("Pod", "serve0-0"))
            evicted_before = cl.metrics.counter("gangs_evicted")
            cl.fail_host(victim.node_name)
            cl.step()
            assert cl.metrics.counter("gangs_evicted") \
                == evicted_before + 1

            for r in pool.drain():
                assert r.rid not in done
                done[r.rid] = r
            assert pool.failovers == 1
            assert 0 in pool.dead_replicas
            assert set(done) == set(rids)
            for rid, (p, n) in rids.items():
                assert done[rid].error is None, (rid, done[rid].error)
                assert done[rid].tokens == solo(params, p, n, cfg), rid
            # the failover also rides the scheduler's metric surface
            assert cl.metrics.counter("serve_failover_total") == 1
            pool.close()
        finally:
            cl.close()


class TestBindConflictRetry:
    """Satellite: a lost optimistic-concurrency race on the extender
    bind path retries with jittered backoff, then requeues — never a
    hard failure."""

    def _cluster(self):
        from kubegpu_tpu.cluster import SimCluster
        return SimCluster(["v4-8"])

    def test_transient_conflict_retried(self, monkeypatch):
        from kubegpu_tpu.cluster import SimCluster, tpu_pod
        from kubegpu_tpu.kubemeta.controlplane import Conflict

        cl = SimCluster(["v4-8"])
        try:
            sched = cl.scheduler
            real = sched.api.bind_pod
            fails = {"n": 2}

            def flaky(*a, **kw):
                if fails["n"] > 0:
                    fails["n"] -= 1
                    raise Conflict("rv race")
                return real(*a, **kw)

            monkeypatch.setattr(sched.api, "bind_pod", flaky)
            monkeypatch.setattr(time, "sleep", lambda s: None)
            cl.api.create("Pod", tpu_pod("solo", chips=1,
                                         command=["noop"]))
            sched.sync()
            err = sched.bind("solo", cl.agents[0].node_name)
            assert err is None
            assert sched.metrics.counter("bind_conflict_retries") == 2
        finally:
            cl.close()

    def test_persistent_conflict_requeues(self, monkeypatch):
        from kubegpu_tpu.cluster import SimCluster, tpu_pod
        from kubegpu_tpu.kubemeta.controlplane import Conflict

        cl = SimCluster(["v4-8"])
        try:
            sched = cl.scheduler

            def always(*a, **kw):
                raise Conflict("rv race")

            monkeypatch.setattr(sched.api, "bind_pod", always)
            monkeypatch.setattr(time, "sleep", lambda s: None)
            cl.api.create("Pod", tpu_pod("solo", chips=1,
                                         command=["noop"]))
            sched.sync()
            err = sched.bind("solo", cl.agents[0].node_name)
            assert err is not None and "requeued" in err
            assert sched.metrics.counter("bind_conflict_requeued") == 1
        finally:
            cl.close()


class TestFusedChaos:
    """Fused multi-tick decode under fault injection (ISSUE 8): a
    quarantine flag raised MID-BLOCK on the device comes home in the
    same fused fetch, truncates that lane's emissions at the poisoned
    tick, and replays bit-exact; replica kill during fused serving
    fails over with the same exactly-once/bit-exact contract.  Windows
    are sized so several fused blocks run (chaos fires at dispatch
    gates — a window that drains in one block never reaches its
    event)."""

    def _eng(self, params, cfg, **kw):
        kw.setdefault("n_slots", 2)
        kw.setdefault("stride", 2)
        kw.setdefault("prompt_buckets", (8, 16))
        kw.setdefault("paged", True)
        kw.setdefault("page_size", 8)
        kw.setdefault("fused_ticks", 4)
        return ContinuousBatcher(params, cfg, **kw)

    def test_mid_block_nan_quarantine_replays_bit_exact(self, tiny):
        """The poison lands on an inner tick of a fused block: the
        on-device bad flag must freeze the lane inside the scan, the
        host must discard that lane's tokens from the poisoned tick on,
        and the replay must reproduce the fault-free stream exactly —
        while the neighbor slot sails through untouched."""
        cfg, params = tiny
        reg = MetricsRegistry()
        eng = self._eng(params, cfg, metrics=reg, chaos=ChaosInjector(
            [ChaosEvent(tick=2, kind="nan_logits")]))
        prompts = [([(i * 3 + 1) % cfg.vocab_size for i in range(5)], 20),
                   ([(i * 5 + 2) % cfg.vocab_size for i in range(7)], 20)]
        rids = {eng.submit(p, n): (p, n) for p, n in prompts}
        seen = {}
        for r in eng.drain():
            assert r.rid not in seen, "duplicate completion"
            seen[r.rid] = r
        assert set(seen) == set(rids)
        assert eng.fused_dispatches > 1, \
            "the fault must land inside fused serving"
        assert eng.slots_quarantined == 1
        assert eng.requests_retried == 1
        assert reg.counter("serve_slots_quarantined") == 1
        for rid, (p, n) in rids.items():
            assert seen[rid].error is None
            assert seen[rid].tokens == solo(params, p, n, cfg), rid

    def test_replica_kill_during_fused_serving(self, tiny):
        """dp=2 pool of fused engines, one replica killed mid-stream:
        failover replays every orphaned request bit-exact on the
        survivor — the fused fetch layout must not confuse the replay
        bookkeeping."""
        cfg, params = tiny
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        pool = DataParallelServePool(
            params, cfg, dp=2, tp=1, n_slots=2, stride=2,
            prompt_buckets=(8, 16), page_size=8, fused_ticks=4,
            chaos={1: ChaosInjector(
                [ChaosEvent(tick=2, kind="kill_replica")])})
        prompts = [(p, 20) for p, _ in mixed_prompts(cfg, n=4)]
        rids = {pool.submit(p, n): (p, n) for p, n in prompts}
        seen = {}
        for r in pool.drain():
            assert r.rid not in seen, f"rid {r.rid} completed twice"
            seen[r.rid] = r
        assert set(seen) == set(rids), "request lost"
        assert pool.failovers == 1
        assert 1 in pool.dead_replicas
        assert sum(e.fused_dispatches for e in pool.replicas
                   if e is not None) > 0
        for rid, (p, n) in rids.items():
            assert seen[rid].error is None, (rid, seen[rid].error)
            assert seen[rid].tokens == solo(params, p, n, cfg), rid


class TestOverloadAdmission:
    """SLO-guarded overload (ISSUE 13): tiered admission, deadline
    pruning, tenant quotas, and low-priority preemption composed with
    the chaos matrix.  Parking is host-side bookkeeping (pages
    released, request requeued), so every fault the engine already
    survives must compose with it — and the strict-across-tiers
    ordering must hold under arbitrary seeded overload."""

    def _eng(self, params, cfg, **kw):
        kw.setdefault("n_slots", 2)
        kw.setdefault("stride", 2)
        kw.setdefault("prompt_buckets", (8, 16))
        kw.setdefault("paged", True)
        kw.setdefault("page_size", 8)
        kw.setdefault("total_pages", 12)
        return ContinuousBatcher(params, cfg, **kw)

    def test_preempt_then_replica_kill_exactly_once_bit_exact(self, tiny):
        """THE composition the issue demands: low-priority requests
        preempted mid-decode to make room for a higher tier, then a
        replica killed while the victims sit parked host-side — after
        failover every request (victims included) still completes
        exactly once with tokens bit-exact vs the solo run."""
        cfg, params = tiny
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        reg = MetricsRegistry()
        pool = DataParallelServePool(
            params, cfg, dp=2, tp=1, n_slots=2, stride=2,
            prompt_buckets=(8, 16), paged=True, page_size=8,
            total_pages=12, metrics=reg,
            chaos={0: ChaosInjector(
                [ChaosEvent(tick=5, kind="kill_replica")])})
        low = [([(i * 3 + j) % cfg.vocab_size for i in range(4 + j)],
                8) for j in range(4)]
        rids = {pool.submit(p, n, tier=2): (p, n) for p, n in low}
        for _ in range(3):          # victims reach mid-decode
            pool.step()
        hi = [([(i * 5 + 7) % cfg.vocab_size for i in range(5)], 6),
              ([(i * 7 + 3) % cfg.vocab_size for i in range(6)], 6)]
        rids.update({pool.submit(p, n, tier=0): (p, n)
                     for p, n in hi})
        seen = {}
        for r in pool.drain():
            assert r.rid not in seen, f"rid {r.rid} completed twice"
            seen[r.rid] = r
        assert set(seen) == set(rids), "request lost"
        assert pool.failovers == 1
        assert 0 in pool.dead_replicas
        assert pool.requests_preempted >= 1, \
            "scenario never exercised preemption"
        assert reg.counter("serve_requests_preempted") >= 1
        for rid, (p, n) in rids.items():
            assert seen[rid].error is None, (rid, seen[rid].error)
            assert seen[rid].tokens == solo(params, p, n, cfg), rid

    def test_preempt_resume_composes_with_nan_quarantine(self, tiny):
        """Engine-level composition: a parked victim resumed through
        greedy replay onto a slot that then takes a NaN poisoning —
        the quarantine replay path and the preemption replay path
        share bookkeeping, and the request must still surface once,
        bit-exact."""
        cfg, params = tiny
        reg = MetricsRegistry()
        eng = self._eng(params, cfg, metrics=reg,
                        chaos=ChaosInjector(
                            [ChaosEvent(tick=5, kind="nan_logits")]))
        low = [([(i * 3 + j) % cfg.vocab_size for i in range(4 + j)],
                8) for j in range(2)]
        rids = {eng.submit(p, n, tier=2): (p, n) for p, n in low}
        for _ in range(3):
            eng.step()
        p_hi = [(i * 5 + 7) % cfg.vocab_size for i in range(5)]
        rids[eng.submit(p_hi, 6, tier=0)] = (p_hi, 6)
        seen = {}
        for r in eng.drain():
            assert r.rid not in seen, f"rid {r.rid} completed twice"
            seen[r.rid] = r
        assert set(seen) == set(rids), "request lost"
        assert eng.requests_preempted >= 1
        assert eng.requests_resumed == eng.requests_preempted
        assert eng.slots_quarantined >= 1, \
            "chaos tick never landed on a live slot"
        for rid, (p, n) in rids.items():
            assert seen[rid].error is None, (rid, seen[rid].error)
            assert seen[rid].tokens == solo(params, p, n, cfg), rid

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_tier_ordering_never_inverted_under_overload(self, tiny,
                                                         seed):
        """Property: under seeded bursty overload, the engine never
        admits a lower-priority request while a higher-priority one
        sits eligible in the queue — checked tick by tick against the
        live queue, not inferred from aggregate timings."""
        cfg, params = tiny
        from kubegpu_tpu.loadgen import LoadSpec, TierSpec, synth_trace
        tiers = tuple(TierSpec(f"t{k}", 10 ** 6, 10 ** 6.0, s)
                      for k, s in enumerate((0.3, 0.4, 0.3)))
        spec = LoadSpec(seed=seed, n_requests=24, mean_iat_ticks=0.7,
                        burst=True, prompt_len_max=8, out_len_min=2,
                        out_len_max=8, vocab=min(48, cfg.vocab_size),
                        tiers=tiers)
        trace = synth_trace(spec)
        reg = MetricsRegistry()
        eng = self._eng(params, cfg, metrics=reg)
        done: dict[int, object] = {}
        i = 0
        max_queue = 0
        for tick in range(600):
            while i < len(trace) and trace[i]["arrival_tick"] <= tick:
                item = trace[i]
                eng.submit(item["prompt"], item["max_new"],
                           tier=item["tier"])
                i += 1
            max_queue = max(max_queue, len(eng.queue))
            eligible = {r.rid: r.tier for r, _ in eng.queue
                        if r.not_before_tick <= eng._step_count}
            for r in eng.step():
                assert r.rid not in done, "duplicate completion"
                done[r.rid] = r
            still = {r.rid for r, _ in eng.queue}
            admitted = [t for rid, t in eligible.items()
                        if rid not in still]
            waiting = [t for rid, t in eligible.items() if rid in still]
            if admitted and waiting:
                assert max(admitted) <= min(waiting), \
                    (seed, tick, admitted, waiting)
            if i >= len(trace) and not eng.queue and not eng.slot_req:
                break
        assert len(done) == len(trace), "run did not drain"
        assert max_queue >= 3, "scenario never actually overloaded"
        assert all(r.error is None for r in done.values())

    def test_deadline_pruned_pre_prefill_lowest_tier_starves_first(
            self, tiny):
        """Satellite (a): a queued low-tier request whose tick deadline
        lapses is pruned BEFORE any prefill work (no tokens, separate
        ``deadline`` shed reason), while a later-submitted tier-0
        request overtakes it and completes — shed lowest tier first,
        never miss a higher tier's SLO to serve a lower one."""
        cfg, params = tiny
        reg = MetricsRegistry()
        eng = self._eng(params, cfg, n_slots=1, metrics=reg)
        p_a = [(i * 3 + 1) % cfg.vocab_size for i in range(5)]
        p_b = [(i * 5 + 2) % cfg.vocab_size for i in range(6)]
        p_c = [(i * 7 + 3) % cfg.vocab_size for i in range(7)]
        ra = eng.submit(p_a, 8, tier=0)             # occupies the slot
        rb = eng.submit(p_b, 6, tier=2, deadline_ticks=4)
        rc = eng.submit(p_c, 6, tier=0)
        done = {r.rid: r for r in eng.drain()}
        assert set(done) == {ra, rb, rc}
        assert done[rb].error == "deadline exceeded"
        assert done[rb].tokens == [], \
            "pruned request burned prefill work"
        for rid, (p, n) in ((ra, (p_a, 8)), (rc, (p_c, 6))):
            assert done[rid].error is None
            assert done[rid].tokens == solo(params, p, n, cfg)
        assert eng.shed_by_reason == {"deadline": 1}
        assert eng.deadline_misses == 1
        assert reg.counter("serve_requests_shed") == 1
        assert reg.counter("serve_requests_shed_deadline") == 1
        assert reg.counter("serve_requests_shed_t2") == 1
        assert reg.counter("serve_deadline_miss") == 1
        assert reg.counter("serve_deadline_miss_t2") == 1

    def test_tenant_quota_sheds_at_door_and_frees_on_finish(self, tiny):
        """Per-tenant quotas bound IN-FLIGHT work: the over-quota
        submit is rejected before queueing (reason ``quota``), other
        tenants are untouched, and finishing a request frees the
        tenant's slot for a later submit."""
        cfg, params = tiny
        reg = MetricsRegistry()
        eng = self._eng(params, cfg, tenant_quotas={"acme": 1},
                        metrics=reg)
        p1 = [1, 2, 3]
        p2 = [4, 5, 6]
        p3 = [7, 8, 9]
        r1 = eng.submit(p1, 5, tenant="acme")
        r2 = eng.submit(p2, 5, tenant="acme")     # over quota: shed
        r3 = eng.submit(p3, 5, tenant="other")
        done = {r.rid: r for r in eng.drain()}
        assert set(done) == {r1, r2, r3}
        assert "quota" in done[r2].error
        assert done[r2].tokens == []
        assert done[r1].tokens == solo(params, p1, 5, cfg)
        assert done[r3].tokens == solo(params, p3, 5, cfg)
        assert eng.shed_by_reason == {"quota": 1}
        assert reg.counter("serve_requests_shed_quota") == 1
        # the quota slot freed with r1 — the tenant can submit again
        r4 = eng.submit(p2, 5, tenant="acme")
        done2 = {r.rid: r for r in eng.drain()}
        assert done2[r4].error is None
        assert done2[r4].tokens == solo(params, p2, 5, cfg)


class TestHealthWatchEdgeCases:
    """Watch-delivery weather the fleet's DomainChaosInjector newly
    exercises (ISSUE 19 sat.): duplicated eviction events, deliveries
    arriving out of issue order, and an eviction for a gang that was
    already drained by the autoscaler — every one an idempotent no-op
    beyond its first effect."""

    def _pool(self, params, cfg, dp=2, **kw):
        kw.setdefault("n_slots", 2)
        kw.setdefault("stride", 2)
        kw.setdefault("prompt_buckets", (8, 16))
        kw.setdefault("page_size", 8)
        pool = DataParallelServePool(params, cfg, dp=dp, tp=1, **kw)
        for i in range(dp):
            pool.bind_replica_gang(i, f"serve{i}")
        return pool

    def test_duplicated_eviction_fails_over_once(self, tiny):
        """The watch redelivers (at-least-once semantics): three
        copies of the same eviction must cost exactly ONE failover."""
        cfg, params = tiny
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        pool = self._pool(params, cfg)
        prompts = mixed_prompts(cfg, n=5)
        rids = {pool.submit(p, n): (p, n) for p, n in prompts}
        for _ in range(3):
            pool.observe_gang_eviction("serve0", "dup delivery")
        assert len(pool._pending_deaths) == 1
        done = {}
        for r in pool.drain():
            assert r.rid not in done, "duplicate completion"
            done[r.rid] = r
        # a straggler duplicate lands AFTER the failover completed
        pool.observe_gang_eviction("serve0", "late duplicate")
        assert not pool._pending_deaths
        assert pool.failovers == 1
        assert set(done) == set(rids)
        for rid, (p, n) in rids.items():
            assert done[rid].error is None
            assert done[rid].tokens == solo(params, p, n, cfg)

    def test_out_of_order_delivery_converges_to_same_state(self, tiny):
        """Evictions issued (serve1 then serve2) but delivered in the
        REVERSE order must reach the same end state: both replicas
        dead, every request exactly once, tokens bit-exact."""
        cfg, params = tiny
        if len(jax.devices()) < 3:
            pytest.skip("needs 3 devices")
        pool = self._pool(params, cfg, dp=3)
        prompts = mixed_prompts(cfg, n=6)
        rids = {pool.submit(p, n): (p, n) for p, n in prompts}
        pool.observe_gang_eviction("serve2", "issued second")
        pool.observe_gang_eviction("serve1", "issued first")
        done = {}
        for r in pool.drain():
            assert r.rid not in done
            done[r.rid] = r
        assert set(pool.dead_replicas) == {1, 2}
        assert pool.failovers == 2
        assert set(done) == set(rids)
        for rid, (p, n) in rids.items():
            assert done[rid].error is None
            assert done[rid].tokens == solo(params, p, n, cfg)

    def test_eviction_of_already_drained_gang_is_noop(self, tiny):
        """The autoscaler's scale-down path: retire_replica drains
        through replay parking, THEN the control plane's eviction for
        that gang arrives on the watch — it must be a no-op, not a
        second failover against a dead replica."""
        cfg, params = tiny
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        pool = self._pool(params, cfg)
        prompts = mixed_prompts(cfg, n=4)
        rids = {pool.submit(p, n): (p, n) for p, n in prompts}
        pool.retire_replica(0)
        done = {r.rid: r for r in pool.drain()}
        assert 0 in pool.dead_replicas and pool.drains == 1
        pool.observe_gang_eviction("serve0", "watch caught up")
        assert not pool._pending_deaths
        pool.step()                       # must not fail anything over
        assert pool.failovers == 0
        assert set(done) == set(rids)
        for rid, (p, n) in rids.items():
            assert done[rid].error is None
            assert done[rid].tokens == solo(params, p, n, cfg)

    def test_chaos_failover_deletes_queue_depth_gauge(self, tiny):
        """Regression (ISSUE 19 sat.): a chaos DEATH must delete the
        per-replica queue-depth gauge just like an autoscale drain
        does — a dead replica frozen at its last depth on /metrics is
        the leak this guards against."""
        cfg, params = tiny
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        reg = MetricsRegistry()
        pool = DataParallelServePool(
            params, cfg, dp=2, tp=1, n_slots=2, stride=2,
            prompt_buckets=(8, 16), page_size=8, metrics=reg,
            chaos={1: ChaosInjector(
                [ChaosEvent(tick=1, kind="kill_replica")])})
        for p, n in mixed_prompts(cfg, n=5):
            pool.submit(p, n)
        pool.drain()
        assert 1 in pool.dead_replicas
        gauges = reg.snapshot()["gauges"]
        assert "serve_replica_queue_depth_r1" not in gauges
        assert "serve_replica_queue_depth_r0" in gauges

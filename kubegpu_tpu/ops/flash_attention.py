"""Flash attention: pallas TPU kernel + XLA reference.

Design per /opt/skills/guides/pallas_guide.md: grid over (batch·heads,
q-blocks); K/V live in VMEM per (b,h); online-softmax accumulation over
k-blocks with a fori_loop; f32 accumulators (`preferred_element_type`);
causal masking via broadcasted iotas.  Falls back to a fused-by-XLA
einsum+softmax implementation off-TPU (and for odd shapes), so every
caller works identically on CPU tests and TPU benches.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def repeat_kv(q: jax.Array, k: jax.Array,
              v: jax.Array) -> tuple[jax.Array, jax.Array]:
    """GQA: repeat kv heads up to the query head count (Hq % Hkv == 0)."""
    hq, hkv = q.shape[1], k.shape[1]
    if hq == hkv:
        return k, v
    if hq % hkv:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    rep = hq // hkv
    return jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1)


def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                  causal: bool = True,
                  scale: float | None = None) -> jax.Array:
    """Reference attention.  q: [B, Hq, T, D]; k/v: [B, Hkv, S, D].
    GQA via ``repeat_kv``.  Causal masking is *end-aligned* when t < s
    (query i attends keys <= i + s - t, the decode/suffix convention)."""
    b, hq, t, d = q.shape
    _, hkv, s, _ = k.shape
    if causal and t > s:
        raise ValueError(
            f"causal attention with more queries ({t}) than keys ({s}) is "
            "ill-defined (queries before the key horizon attend nothing)")
    k, v = repeat_kv(q, k, v)
    scale = scale if scale is not None else d ** -0.5
    scores = jnp.einsum("bhtd,bhsd->bhts", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, s), dtype=bool), k=s - t)
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhts,bhsd->bhtd", probs.astype(v.dtype), v)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, block_q: int = 128,
                    block_k: int = 128,
                    interpret: bool = False) -> jax.Array:
    """Pallas flash attention.  Shapes as ``xla_attention`` (GQA folded
    by repeating kv heads before the kernel — the bandwidth win of true
    grouped reads is a later-round optimization)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, hq, t, d = q.shape
    _, hkv, s, _ = k.shape
    if causal and t > s:
        raise ValueError(
            f"causal attention with more queries ({t}) than keys ({s}) is "
            "ill-defined (queries before the key horizon attend nothing)")
    k, v = repeat_kv(q, k, v)
    scale = d ** -0.5
    causal_offset = s - t  # end-aligned, matching xla_attention
    block_q = min(block_q, t)
    block_k = min(block_k, s)
    if t % block_q or s % block_k:
        return xla_attention(q, k, v, causal=causal)

    qf = q.reshape(b * hq, t, d)
    kf = k.reshape(b * hq, s, d)
    vf = v.reshape(b * hq, s, d)
    num_k_blocks = s // block_k

    def kernel(q_ref, k_ref, v_ref, o_ref):
        qi = pl.program_id(1)
        qb = q_ref[0].astype(jnp.float32) * scale  # [bq, d]

        def body(ki, carry):
            o_acc, m_acc, l_acc = carry
            kb = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
            vb = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
            sc = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [bq, bk]
            if causal:
                qpos = causal_offset + qi * block_q + \
                    jax.lax.broadcasted_iota(
                        jnp.int32, (block_q, block_k), 0)
                kpos = ki * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                sc = jnp.where(qpos >= kpos, sc, NEG_INF)
            m_new = jnp.maximum(m_acc, sc.max(axis=-1, keepdims=True))
            p = jnp.exp(sc - m_new)
            alpha = jnp.exp(m_acc - m_new)
            l_new = alpha * l_acc + p.sum(axis=-1, keepdims=True)
            o_new = alpha * o_acc + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return o_new, m_new, l_new

        o0 = jnp.zeros((block_q, d), jnp.float32)
        m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
        l0 = jnp.zeros((block_q, 1), jnp.float32)
        if causal:
            # k-blocks strictly past this q-block's LAST row's horizon
            # contribute nothing; the last visible key index is
            # offset + (qi+1)*block_q - 1.
            horizon = causal_offset + (qi + 1) * block_q - 1
            n_iter = jnp.minimum(num_k_blocks, horizon // block_k + 1)
        else:
            n_iter = num_k_blocks
        o_acc, m_acc, l_acc = jax.lax.fori_loop(0, n_iter, body,
                                                (o0, m0, l0))
        o_ref[0] = (o_acc / jnp.maximum(l_acc, 1e-30)).astype(o_ref.dtype)

    grid = (b * hq, t // block_q)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, hq, t, d)


def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              causal: bool = True, impl: str = "auto") -> jax.Array:
    """Dispatch: pallas on TPU, XLA elsewhere.  ``impl`` ∈ auto | pallas |
    pallas_interpret | xla."""
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "pallas":
        return flash_attention(q, k, v, causal=causal)
    if impl == "pallas_interpret":
        return flash_attention(q, k, v, causal=causal, interpret=True)
    return xla_attention(q, k, v, causal=causal)

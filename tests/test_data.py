"""Input pipeline: sharding determinism/disjointness, prefetch, global
batch assembly on the virtual device mesh."""

import numpy as np
import pytest

from kubegpu_tpu.workloads.data import (
    Shard,
    ShardedBatcher,
    global_batches,
    prefetch_to_device,
    synthetic_images,
    synthetic_tokens,
)


def batcher(shard=None, n=100, bs=8, **kw):
    data = {"x": np.arange(n * 3).reshape(n, 3),
            "y": np.arange(n)}
    return ShardedBatcher(data, batch_size=bs, shard=shard, **kw)


class TestSharding:
    def test_workers_partition_each_epoch(self):
        """4 workers' indices are disjoint and cover n - tail."""
        workers = [batcher(Shard(i, 4)) for i in range(4)]
        for epoch in (0, 1):
            all_idx = np.concatenate(
                [w.epoch_indices(epoch) for w in workers])
            assert len(all_idx) == len(set(all_idx)) == 100  # 100%4==0
            assert set(all_idx) == set(range(100))

    def test_same_seed_same_epoch_deterministic(self):
        a = batcher(Shard(1, 4)).epoch_indices(3)
        b = batcher(Shard(1, 4)).epoch_indices(3)
        np.testing.assert_array_equal(a, b)

    def test_epochs_reshuffle(self):
        w = batcher(Shard(0, 2))
        assert not np.array_equal(w.epoch_indices(0), w.epoch_indices(1))

    def test_no_shuffle_is_contiguous(self):
        w = batcher(Shard(1, 2), shuffle=False)
        np.testing.assert_array_equal(w.epoch_indices(0),
                                      np.arange(50, 100))

    def test_batches_align_features_and_labels(self):
        for b in batcher(Shard(0, 1)).batches():
            np.testing.assert_array_equal(b["x"][:, 0], b["y"] * 3)
            assert b["x"].shape == (8, 3)

    def test_drop_remainder_static_shapes(self):
        shapes = {b["y"].shape for b in batcher(n=30, bs=8).batches()}
        assert shapes == {(8,)}  # 30//8=3 full batches, tail dropped
        total = sum(len(b["y"]) for b in batcher(
            n=30, bs=8, drop_remainder=False).batches())
        assert total == 30

    def test_endless_iter_crosses_epochs(self):
        it = iter(batcher(n=16, bs=8))
        seen = [next(it)["y"] for _ in range(4)]  # 2 epochs' worth
        assert sorted(np.concatenate(seen[:2]).tolist()) == list(range(16))

    def test_validation(self):
        with pytest.raises(ValueError, match="shard"):
            Shard(4, 4)
        with pytest.raises(ValueError, match="leading dims"):
            ShardedBatcher({"a": np.zeros(3), "b": np.zeros(4)}, 2)
        with pytest.raises(ValueError, match="shard"):
            ShardedBatcher({"a": np.zeros(2)}, 1, shard=Shard(0, 4))


class TestDevicePipeline:
    def test_prefetch_preserves_order_and_values(self):
        src = batcher(n=40, bs=8)
        plain = list(src.batches(0))
        fetched = list(prefetch_to_device(src.batches(0), size=2))
        assert len(fetched) == len(plain)
        for p, f in zip(plain, fetched):
            np.testing.assert_array_equal(p["x"], np.asarray(f["x"]))
        import jax
        assert isinstance(fetched[0]["x"], jax.Array)

    def test_prefetch_short_stream(self):
        out = list(prefetch_to_device(batcher(n=8, bs=8).batches(0),
                                      size=4))
        assert len(out) == 1

    def test_global_batches_on_mesh(self):
        """dp-sharded global assembly on the 8-device CPU mesh."""
        import jax
        from jax.sharding import PartitionSpec as P

        from kubegpu_tpu.parallel import make_mesh

        mesh = make_mesh({"dp": 8})
        src = batcher(n=64, bs=16)
        for g in global_batches(src.batches(0), mesh, P("dp")):
            assert g["y"].shape == (16,)
            assert len(g["y"].sharding.device_set) == 8
            break

    def test_synthetic_sources_deterministic(self):
        a = synthetic_tokens(10, 16, 100, seed=5)["tokens"]
        b = synthetic_tokens(10, 16, 100, seed=5)["tokens"]
        np.testing.assert_array_equal(a, b)
        imgs = synthetic_images(4, 8, 10)
        assert imgs["images"].shape == (4, 8, 8, 3)
        assert imgs["labels"].shape == (4,)

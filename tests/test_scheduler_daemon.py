"""The scheduler as its OWN process over the HTTP wire (VERDICT r2
missing #1): apiserver daemon + scheduler daemon + node daemon as THREE
processes, this test talking to the control plane only via
HttpApiClient — the reference's deployment topology with no in-process
shortcut anywhere.  Plus the restart drill: kill the scheduler mid-life
and prove annotation truth rebuilds its occupancy."""

import subprocess
import sys
import time

import pytest

from kubegpu_tpu.cluster import tpu_pod
from kubegpu_tpu.kubemeta import FakeApiServer, GangSpec, PodPhase
from kubegpu_tpu.kubemeta.apiserver_http import ApiServerHTTP, HttpApiClient


def _spawn(mod: str, *args: str) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-m", mod, *args],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def _stop(*procs: subprocess.Popen) -> None:
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


def _wait(cond, timeout=90.0, interval=0.1, what="condition"):
    # generous: three cold python processes importing jax under a
    # loaded CI machine can take tens of seconds to come up
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return
        except OSError:
            pass
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def _free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestDaemonBuilder:
    def test_build_scheduler_wires_cache(self):
        """daemon.build_scheduler constructs client → cache → scheduler
        → recovery; a pod scheduled through it binds on the server."""
        import argparse

        from kubegpu_tpu.crishim.agent import NodeAgent
        from kubegpu_tpu.crishim.runtime import FakeRuntime
        from kubegpu_tpu.scheduler.daemon import build_scheduler
        from kubegpu_tpu.tpuplugin import MockBackend

        api = FakeApiServer()
        srv = ApiServerHTTP(api).start()
        agent = NodeAgent(api, MockBackend("v4-8"), FakeRuntime())
        agent.register()
        args = argparse.Namespace(apiserver=srv.address, gang_grace=30.0)
        client, cache, sched, recovery = build_scheduler(args)
        try:
            from kubegpu_tpu.kubemeta.cache import WatchCachedApiClient
            assert isinstance(sched.api, WatchCachedApiClient)
            api.create("Pod", tpu_pod("p", chips=1, command=["x"]))
            _wait(lambda: cache.list("Pod"), timeout=5,
                  what="watch delivery")
            recovery.run_once()
            res = sched.run_once()
            assert res.scheduled == ["p"]
            assert api.get("Pod", "p").status.phase == PodPhase.SCHEDULED
        finally:
            recovery.close()
            cache.close()
            client.close()
            srv.close()


class TestDaemonMetrics:
    @pytest.mark.slow
    def test_metrics_endpoint_over_http(self):
        """--metrics-port serves the Prometheus surface from the
        scheduler daemon process; after a pod schedules, the
        schedule-latency summary must be present."""
        import urllib.request

        from kubegpu_tpu.crishim.agent import NodeAgent
        from kubegpu_tpu.crishim.runtime import FakeRuntime
        from kubegpu_tpu.tpuplugin import MockBackend

        api = FakeApiServer()
        srv = ApiServerHTTP(api).start()
        agent = NodeAgent(api, MockBackend("v4-8"), FakeRuntime())
        agent.register()
        mport = _free_port()
        sch = _spawn("kubegpu_tpu.scheduler.daemon",
                     "--apiserver", srv.address, "--tick", "0.2",
                     "--metrics-port", str(mport))
        try:
            api.create("Pod", tpu_pod("m", chips=1, command=["x"]))
            _wait(lambda: api.get("Pod", "m").status.phase
                  == PodPhase.SCHEDULED, what="pod scheduled")
            _wait(lambda: b"schedule_latency_ms" in urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/metrics", timeout=5).read(),
                timeout=15, what="metrics endpoint")
        finally:
            _stop(sch)
            srv.close()


class TestWireBench:
    @pytest.mark.slow
    def test_wire_bench_structure(self):
        """run_wire_bench (the recorded scheduler-over-HTTP p50) must
        keep producing its percentile document."""
        from kubegpu_tpu.benchmark import run_wire_bench

        out = run_wire_bench(n_pods=6, slice_type="v4-8")
        assert out["n_pods"] == 6
        assert 0 < out["p50_ms"] <= out["p99_ms"] <= out["max_ms"]


class TestThreeProcessControlPlane:
    @pytest.mark.slow
    def test_pod_e2e_three_processes(self):
        """submit → (HTTP) apiserver process → watched by the scheduler
        process (cached reads, wire binds) → node daemon process → real
        workload subprocess → SUCCEEDED, observed back over HTTP."""
        port = _free_port()
        url = f"http://127.0.0.1:{port}"
        aps = _spawn("kubegpu_tpu.kubemeta.apiserver_serve",
                     "--port", str(port))
        sch = _spawn("kubegpu_tpu.scheduler.daemon",
                     "--apiserver", url, "--tick", "0.2")
        nod = _spawn("kubegpu_tpu.crishim.serve", "--apiserver", url,
                     "--backend", "mock", "--slice", "v4-8",
                     "--real-processes", "--tick", "0.05",
                     "--advertise-interval", "1",
                     "--env", "JAX_PLATFORMS=cpu")
        client = None
        try:
            client = HttpApiClient(url)
            _wait(lambda: client.list("Node"), what="node registration")
            client.create("Pod", tpu_pod(
                "hello", chips=1,
                command=[sys.executable, "-c", "print('ran')"]))
            _wait(lambda: client.get("Pod", "hello").status.phase
                  == PodPhase.SUCCEEDED, what="pod completion")
            pod = client.get("Pod", "hello")
            assert pod.spec.node_name, "pod completed but never bound?"
            for p, name in ((aps, "apiserver"), (sch, "scheduler"),
                            (nod, "node daemon")):
                assert p.poll() is None, f"{name} died"
        finally:
            if client is not None:
                client.close()
            _stop(sch, nod, aps)

    @pytest.mark.slow
    def test_scheduler_restart_rebuilds_occupancy(self):
        """Kill the scheduler daemon after it commits a slice-filling
        gang; a fresh daemon must rebuild that occupancy from pod
        ANNOTATIONS (not memory): an extra pod stays Pending until the
        gang's pods are deleted, then schedules.  Apiserver lives
        in-process here so the test can also inspect server state; the
        scheduler still only ever sees the HTTP wire."""
        from kubegpu_tpu.crishim.agent import NodeAgent
        from kubegpu_tpu.crishim.runtime import FakeRuntime
        from kubegpu_tpu.tpuplugin import MockBackend

        api = FakeApiServer()
        srv = ApiServerHTTP(api).start()
        url = srv.address
        # node side in-process (its wire path has its own tests): a
        # v4-8 node advertising 4 whole chips
        backend = MockBackend("v4-8")
        agent = NodeAgent(api, backend, FakeRuntime())
        agent.register()

        def gang_pod(name, idx, size):
            return tpu_pod(name, chips=2, command=["x"],
                           gang=GangSpec(name="g", size=size, index=idx))

        sch = _spawn("kubegpu_tpu.scheduler.daemon",
                     "--apiserver", url, "--tick", "0.2")
        try:
            # 2-pod gang x 2 chips fills the 4-chip slice
            api.create("Pod", gang_pod("g-0", 0, 2))
            api.create("Pod", gang_pod("g-1", 1, 2))
            _wait(lambda: all(
                api.get("Pod", n).status.phase == PodPhase.SCHEDULED
                for n in ("g-0", "g-1")), what="gang bound")

            _stop(sch)   # kill the scheduler mid-life
            api.create("Pod", tpu_pod("late", chips=1, command=["x"]))

            sch = _spawn("kubegpu_tpu.scheduler.daemon",
                         "--apiserver", url, "--tick", "0.2")
            _wait(lambda: "connected" in (sch.stdout.readline() or ""),
                  timeout=30, what="scheduler restart")
            # the restarted daemon must NOT place `late`: annotation
            # truth says the slice is full.  Give it a few passes.
            time.sleep(2.0)
            assert api.get("Pod", "late").status.phase \
                == PodPhase.PENDING, \
                "restarted scheduler double-allocated a full slice"

            # freeing the gang releases the chips — the event-driven
            # daemon reacts and places the waiter
            api.delete("Pod", "g-0")
            api.delete("Pod", "g-1")
            _wait(lambda: api.get("Pod", "late").status.phase
                  == PodPhase.SCHEDULED, what="late pod scheduled")
        finally:
            _stop(sch)
            srv.close()

"""Full-fidelity object ⇄ JSON-document codecs for the apiserver wire.

The annotation codec (codec.py) converts *payloads* that ride on
objects; this module converts the OBJECTS themselves — Pod/Node/Quota
with uid, resourceVersion, status, and spec intact — so the HTTP
apiserver façade (apiserver_http.py) can ship them between processes
losslessly.  Document shape follows k8s convention
(metadata/spec/status); the webhook's ExtenderArgs pod documents are a
compatible subset (webhook.pod_from_doc reads scheduler-relevant fields
only, by design — kube-scheduler strips status anyway).
"""

from __future__ import annotations

from kubegpu_tpu.kubemeta.objects import (
    ContainerSpec,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodPhase,
    PodSpec,
    PodStatus,
    Quota,
    QuotaSpec,
    ResourceRequests,
)


def _meta_to_doc(m: ObjectMeta) -> dict:
    return {
        "name": m.name,
        "namespace": m.namespace,
        "labels": dict(m.labels),
        "annotations": dict(m.annotations),
        "uid": m.uid,
        "resourceVersion": m.resource_version,
    }


def _meta_from_doc(d: dict) -> ObjectMeta:
    meta = ObjectMeta(
        name=d["name"],
        namespace=d.get("namespace", "default"),
        labels=dict(d.get("labels") or {}),
        annotations=dict(d.get("annotations") or {}),
    )
    # uid/rv are server-assigned; present on the wire for reads, absent
    # (and freshly generated / zero) on creates
    if d.get("uid"):
        meta.uid = d["uid"]
    meta.resource_version = int(d.get("resourceVersion", 0))
    return meta


def pod_to_doc(pod: Pod) -> dict:
    return {
        "kind": "Pod",
        "metadata": _meta_to_doc(pod.metadata),
        "spec": {
            "nodeName": pod.spec.node_name,
            "schedulerName": pod.spec.scheduler_name,
            "priority": pod.spec.priority,
            "containers": [
                {
                    "name": c.name,
                    "image": c.image,
                    "command": list(c.command),
                    "env": [{"name": k, "value": v}
                            for k, v in c.env.items()],
                    "resources": {"requests": c.resources.to_dict()},
                }
                for c in pod.spec.containers
            ],
        },
        "status": {
            "phase": pod.status.phase.value,
            "message": pod.status.message,
            "exitCode": pod.status.exit_code,
        },
    }


def pod_from_doc(doc: dict) -> Pod:
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    containers = []
    for c in spec.get("containers") or []:
        requests = (c.get("resources") or {}).get("requests") or {}
        containers.append(ContainerSpec(
            name=c.get("name", "main"),
            image=c.get("image", "kubetpu/runtime:latest"),
            command=[str(x) for x in c.get("command") or []],
            env={e["name"]: str(e.get("value", ""))
                 for e in c.get("env") or []},
            resources=ResourceRequests.from_dict(
                {k: float(v) for k, v in requests.items()}),
        ))
    return Pod(
        metadata=_meta_from_doc(doc.get("metadata") or {}),
        spec=PodSpec(
            containers=containers,
            node_name=spec.get("nodeName"),
            scheduler_name=spec.get("schedulerName", "kubetpu-scheduler"),
            priority=int(spec.get("priority", 0)),
        ),
        status=PodStatus(
            phase=PodPhase(status.get("phase", "Pending")),
            message=status.get("message", ""),
            exit_code=status.get("exitCode"),
        ),
    )


def node_to_doc(node: Node) -> dict:
    return {
        "kind": "Node",
        "metadata": _meta_to_doc(node.metadata),
        "status": {"ready": node.status.ready},
    }


def node_from_doc(doc: dict) -> Node:
    status = doc.get("status") or {}
    return Node(
        metadata=_meta_from_doc(doc.get("metadata") or {}),
        status=NodeStatus(ready=bool(status.get("ready", True))),
    )


def quota_to_doc(quota: Quota) -> dict:
    return {
        "kind": "Quota",
        "metadata": _meta_to_doc(quota.metadata),
        "spec": {
            "tpuChips": quota.spec.tpu_chips,
            "millitpu": quota.spec.millitpu,
        },
    }


def quota_from_doc(doc: dict) -> Quota:
    spec = doc.get("spec") or {}
    return Quota(
        metadata=_meta_from_doc(doc.get("metadata") or {}),
        spec=QuotaSpec(
            tpu_chips=spec.get("tpuChips"),
            millitpu=spec.get("millitpu"),
        ),
    )


TO_DOC = {"Pod": pod_to_doc, "Node": node_to_doc, "Quota": quota_to_doc}
FROM_DOC = {"Pod": pod_from_doc, "Node": node_from_doc,
            "Quota": quota_from_doc}


def to_doc(kind: str, obj) -> dict:
    return TO_DOC[kind](obj)


def from_doc(kind: str, doc: dict):
    return FROM_DOC[kind](doc)

"""Metadata transport — reference: ``kubeinterface`` + the k8s apiserver.

The reference's key architectural property (SURVEY.md §2): scheduler and
node agent NEVER talk directly — all coordination rides on Node/Pod
annotations through the apiserver, making every component independently
restartable and testable against a fake apiserver.  KubeTPU preserves this:
``objects`` are k8s-shaped dataclasses, ``codec`` converts advertisement /
request / allocation structs ⇄ annotation JSON, and ``controlplane`` is the
in-process fake apiserver (create/get/list/patch/delete/watch) the whole
test suite runs against (SURVEY.md §5 "simulated control plane").
"""

from kubegpu_tpu.kubemeta.objects import (
    ContainerSpec,
    GangSpec,
    Node,
    ObjectMeta,
    Pod,
    PodPhase,
    PodSpec,
    Quota,
    QuotaSpec,
    ResourceRequests,
)
from kubegpu_tpu.kubemeta.codec import (
    ALLOCATE_FROM_KEY,
    DEVICE_INFO_KEY,
    GANG_KEY,
    MESH_AXES_KEY,
    AllocatedChip,
    Allocation,
    advertise_on_node,
    allocation_from_annotation,
    allocation_to_annotation,
    node_advertisement,
    node_advertisement_from_annotation,
    node_advertisement_to_annotation,
    pod_allocation,
    pod_gang_spec,
    pod_mesh_axes,
    pod_migratable,
    pod_multislice,
    set_pod_allocation,
    set_pod_gang,
    set_pod_mesh_axes,
    set_pod_migratable,
    set_pod_multislice,
)
from kubegpu_tpu.kubemeta.controlplane import (
    Conflict,
    FakeApiServer,
    NotFound,
    WatchEvent,
)
from kubegpu_tpu.kubemeta.apiserver_http import ApiServerHTTP, HttpApiClient
from kubegpu_tpu.kubemeta.serialize import from_doc, to_doc

__all__ = [
    "ContainerSpec", "GangSpec", "Node", "ObjectMeta", "Pod", "PodPhase",
    "PodSpec", "Quota", "QuotaSpec", "ResourceRequests",
    "ALLOCATE_FROM_KEY", "DEVICE_INFO_KEY", "GANG_KEY", "MESH_AXES_KEY",
    "AllocatedChip", "Allocation", "advertise_on_node",
    "allocation_from_annotation", "allocation_to_annotation",
    "node_advertisement", "node_advertisement_from_annotation",
    "node_advertisement_to_annotation", "pod_allocation", "pod_gang_spec",
    "pod_mesh_axes", "pod_migratable", "pod_multislice",
    "set_pod_allocation", "set_pod_migratable",
    "set_pod_gang", "set_pod_mesh_axes", "set_pod_multislice",
    "Conflict", "FakeApiServer", "NotFound", "WatchEvent",
    "ApiServerHTTP", "HttpApiClient", "from_doc", "to_doc",
]

"""End-to-end slice (SURVEY.md §8): mock node → advertise → schedule →
annotation → crishim injection → subprocess runs a real JAX program that
asserts its injected env and trains.  The full §4.5 system traversal."""

import pytest

from kubegpu_tpu.cluster import SimCluster, tpu_pod
from kubegpu_tpu.kubemeta import GangSpec, PodPhase
from kubegpu_tpu.kubemeta.codec import pod_allocation

MNIST = ["python", "-m", "kubegpu_tpu.workloads.programs.mnist_mlp"]


class TestFakeRuntimePath:
    """Scheduling + injection correctness without real processes."""

    def test_single_chip_pod_full_path(self):
        cl = SimCluster(["v4-8"])
        cl.submit(tpu_pod("resnet", chips=1, command=["noop"]))
        result, started = cl.step()
        assert result.scheduled == ["resnet"]
        assert len(started) == 1
        env = started[0].env
        assert len(env["TPU_VISIBLE_CHIPS"].split(",")) == 1
        assert env["TPU_WORKER_ID"] == "0"
        assert env["JAX_NUM_PROCESSES"] == "1"
        alloc = pod_allocation(cl.api.get("Pod", "resnet"))
        assert alloc is not None
        assert len(alloc.chips) == 1

    def test_zero_device_pod_cpu_fallback(self):
        """BASELINE config 1: 0-device request binds with no allocation."""
        cl = SimCluster(["v4-8"])
        cl.submit(tpu_pod("mnist-cpu", chips=0, command=["noop"]))
        result, started = cl.step()
        assert result.scheduled == ["mnist-cpu"]
        env = started[0].env
        assert env["TPU_VISIBLE_CHIPS"] == ""
        assert pod_allocation(cl.api.get("Pod", "mnist-cpu")) is None

    def test_gang_waits_for_all_members(self):
        cl = SimCluster(["v4-8"])
        g = lambda i: GangSpec(name="dpjob", size=4, index=i)
        cl.submit(tpu_pod("dp-0", chips=1, gang=g(0), command=["noop"]))
        cl.submit(tpu_pod("dp-1", chips=1, gang=g(1), command=["noop"]))
        result, started = cl.step()
        assert result.scheduled == []
        assert set(result.held) == {"dp-0", "dp-1"}
        assert started == []
        # remaining members arrive → whole gang goes at once
        cl.submit(tpu_pod("dp-2", chips=1, gang=g(2), command=["noop"]))
        cl.submit(tpu_pod("dp-3", chips=1, gang=g(3), command=["noop"]))
        result, started = cl.step()
        assert len(result.scheduled) == 4
        assert len(started) == 4
        # worker ids follow gang indices; all share one coordinator
        envs = {h.pod_name: h.env for h in started}
        assert [envs[f"dp-{i}"]["TPU_WORKER_ID"] for i in range(4)] == \
            ["0", "1", "2", "3"]
        assert len({e["JAX_COORDINATOR_ADDRESS"]
                    for e in envs.values()}) == 1
        # 4 distinct chips on the single v4-8 host
        chips = {e["TPU_VISIBLE_CHIPS"] for e in envs.values()}
        assert len(chips) == 4

    def test_multihost_gang_spans_hosts(self):
        """BASELINE config 4 shape: 4 pods x 4 chips over v5e-16."""
        cl = SimCluster(["v5e-16"])
        for i in range(4):
            cl.submit(tpu_pod(f"llama-{i}", chips=4,
                              gang=GangSpec(name="llama", size=4, index=i),
                              mesh_axes={"dp": 4, "tp": 4},
                              command=["noop"]))
        result, started = cl.step()
        assert len(result.scheduled) == 4
        nodes = {cl.api.get("Pod", f"llama-{i}").spec.node_name
                 for i in range(4)}
        assert len(nodes) == 4  # one pod per host
        hostnames = {h.env["TPU_WORKER_HOSTNAMES"] for h in started}
        assert len(hostnames) == 1  # all agree on the roster

    def test_multitenant_fractional_plus_gang(self):
        """BASELINE config 5: fractional pods co-tenant with a slice job."""
        cl = SimCluster(["v4-8"])
        cl.submit(tpu_pod("frac-a", millitpu=300, command=["noop"]))
        cl.submit(tpu_pod("frac-b", millitpu=600, command=["noop"]))
        for i in range(3):
            cl.submit(tpu_pod(f"gang-{i}", chips=1,
                              gang=GangSpec(name="g3", size=3, index=i),
                              command=["noop"]))
        result, _ = cl.step()
        assert len(result.scheduled) == 5
        # fractional pods share one chip; gang gets 3 whole other chips
        fa = pod_allocation(cl.api.get("Pod", "frac-a")).chips[0]
        fb = pod_allocation(cl.api.get("Pod", "frac-b")).chips[0]
        assert fa.coord == fb.coord
        gang_coords = {pod_allocation(cl.api.get("Pod", f"gang-{i}")
                                      ).chips[0].coord for i in range(3)}
        assert fa.coord not in gang_coords
        assert len(gang_coords) == 3

    def test_resources_returned_on_completion(self):
        cl = SimCluster(["v4-8"])
        cl.submit(tpu_pod("a", chips=4, command=["noop"]))
        cl.step()
        st = next(iter(cl.scheduler.slices.values()))
        assert sum(st.used_millichips.values()) == 4000
        cl.reap()  # FakeRuntime exits 0 instantly → Succeeded → release
        assert cl.pod_phase("a") == PodPhase.SUCCEEDED
        assert sum(st.used_millichips.values()) == 0
        # slice reusable
        cl.submit(tpu_pod("b", chips=4, command=["noop"]))
        result, _ = cl.step()
        assert result.scheduled == ["b"]

    def test_scheduler_restart_recovers_from_annotations(self):
        """SURVEY.md §4.4: rebuild Used purely from pod annotations."""
        from kubegpu_tpu.scheduler import DeviceScheduler
        cl = SimCluster(["v5e-16"])
        cl.submit(tpu_pod("a", chips=4, command=["noop"]))
        cl.submit(tpu_pod("b", chips=2, command=["noop"]))
        cl.step()
        old_used = {
            sid: dict(st.used_millichips)
            for sid, st in cl.scheduler.slices.items()}
        fresh = DeviceScheduler(cl.api)  # brand-new process, same apiserver
        new_used = {
            sid: {k: v for k, v in st.used_millichips.items() if v}
            for sid, st in fresh.slices.items()}
        old_used = {
            sid: {k: v for k, v in used.items() if v}
            for sid, used in old_used.items()}
        assert new_used == old_used

    def test_unschedulable_oversize(self):
        cl = SimCluster(["v4-8"])
        cl.submit(tpu_pod("big", chips=8, command=["noop"]))
        result, _ = cl.step()
        assert result.scheduled == []
        assert result.unschedulable == ["big"]

    def test_schedule_latency_metric_populated(self):
        cl = SimCluster(["v4-8"])
        cl.submit(tpu_pod("a", chips=1, command=["noop"]))
        cl.step()
        snap = cl.metrics.snapshot()
        assert snap["histograms"]["schedule_latency_ms"]["count"] == 1
        assert cl.trace.events("schedule")


@pytest.mark.slow
class TestRealProcessPath:
    """The full traversal with real subprocesses running real JAX on CPU."""

    def test_mnist_single_pod_trains(self):
        cl = SimCluster(["v4-8"], real_processes=True,
                        extra_env={"JAX_PLATFORMS": "cpu"})
        try:
            cl.submit(tpu_pod("mnist", chips=1, command=MNIST,
                              env={"KUBETPU_EXPECT_CHIPS": "1"}))
            codes = cl.run_to_completion(timeout_s=120)
            assert codes.get("mnist") == 0, \
                cl.api.get("Pod", "mnist").status.message
            assert cl.pod_phase("mnist") == PodPhase.SUCCEEDED
        finally:
            cl.close()

    def test_mnist_zero_device_cpu_fallback(self):
        """BASELINE config 1 end-to-end: CPU-only pod runs the trainer."""
        cl = SimCluster(["v4-8"], real_processes=True)
        try:
            cl.submit(tpu_pod("mnist-cpu", chips=0, command=MNIST,
                              env={"KUBETPU_EXPECT_CHIPS": "0"}))
            codes = cl.run_to_completion(timeout_s=120)
            assert codes.get("mnist-cpu") == 0
        finally:
            cl.close()


class TestWorkloadMetricsHarvest:
    def test_harvest_parses_metric_lines(self):
        from kubegpu_tpu.crishim.agent import harvest_workload_metrics
        from kubegpu_tpu.obs import MetricsRegistry

        m = MetricsRegistry()
        stdout = (
            "some log line\n"
            '{"metric": "allreduce_algo_bandwidth", "value": 12.5, '
            '"unit": "GiB/s", "devices": 4}\n'
            '{"not": "a metric"}\n'
            '{"metric": "bad", "value": "NaN-ish-string"}\n'
            "trailing text\n")
        seen = harvest_workload_metrics(stdout, m)
        assert seen == ["allreduce_algo_bandwidth"]
        snap = m.snapshot()
        assert snap["gauges"]["workload_allreduce_algo_bandwidth"] == 12.5
        h = snap["histograms"]["workload_allreduce_algo_bandwidth"]
        assert h["count"] == 1

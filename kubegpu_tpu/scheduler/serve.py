"""Standalone extender service: ``python -m kubegpu_tpu.scheduler.serve``.

Binds the HTTP extender webhook (deploy/README.md §1) over a cluster
built from the config tree — the mock backend in this environment, the
same wiring a real deployment uses with a client-go-backed apiserver
shim in place of the fake.  Prints the policy-config stanza to register
with kube-scheduler, then serves until interrupted.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: list[str] | None = None) -> int:
    from kubegpu_tpu.cluster import SimCluster
    from kubegpu_tpu.config import KubeTpuConfig
    from kubegpu_tpu.scheduler.webhook import (
        ExtenderHTTPServer,
        policy_config,
    )

    ap = argparse.ArgumentParser(
        prog="kubetpu-extender",
        description="HTTP scheduler-extender webhook (kube-scheduler "
        "filter/prioritize verbs)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8900)
    ap.add_argument("--advertise-url",
                    help="reachable URL for the printed policy stanza "
                    "(e.g. the Service DNS name); defaults to the bind "
                    "address, or the kube-system Service name when "
                    "binding 0.0.0.0")
    ap.add_argument("--config", help="config file (JSON/YAML)")
    ap.add_argument("--set", action="append", metavar="K.EY=VAL",
                    help="dotted config override, repeatable")
    ap.add_argument("--slices", nargs="+",
                    help="override cluster slice types")
    args = ap.parse_args(argv)

    cfg = KubeTpuConfig.load(args.config, args.set or [])
    if args.slices:
        cfg.backend.slice_types = args.slices
    cl = SimCluster.from_config(cfg)
    srv = ExtenderHTTPServer(cl.scheduler, host=args.host,
                             port=args.port).start()
    print(f"extender listening on {srv.address}", file=sys.stderr)
    # the stanza must carry an address kube-scheduler can REACH — the
    # bind address is wrong for 0.0.0.0 (that's kube-scheduler's own host)
    bound_port = srv.address.rsplit(":", 1)[1]   # actual port (ephemeral
    advertise = args.advertise_url or (          # binds resolve to real)
        f"http://kubetpu-extender.kube-system.svc:{bound_port}"
        if args.host == "0.0.0.0" else srv.address)
    print(json.dumps(policy_config(advertise), indent=2))
    try:
        import threading
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        srv.close()
        cl.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

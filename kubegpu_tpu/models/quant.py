"""Weight-only int8 quantization for serving (TPU-native addition).

Autoregressive decode is HBM-bandwidth-bound: every generated token
streams the full weight set through the MXU, so halving weight bytes
(bf16 → int8 + per-channel scales) is a direct ~2x on the decode
bottleneck.  Classic symmetric per-output-channel scheme (AWQ/GPTQ-free
round-to-nearest — adequate at 8 bits).

Design: :class:`QTensor` is a pytree-registered (int8 values, f32
per-channel scale) pair whose ``@`` overloads dequantize lazily inside
the jitted graph — ``x @ qw`` traces as ``(x @ values.astype(x.dtype)) *
scale``, which XLA fuses into the matmul epilogue.  Because the model
code only ever uses weights via ``@``, :func:`quantize_params` can swap
leaves in place and the existing Llama forward / KV-cache decode run
UNCHANGED on a quantized tree (norms, embeddings, and biases stay in
full precision; embedding stays because it is consumed by ``take``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class QTensor:
    """Symmetric per-output-channel int8 weight: ``values`` [..., out]
    int8, ``scale`` f32 broadcastable against ``values`` (reduced axes
    kept as size-1, so stacked [L, 1, out] scales slice in lockstep with
    [L, in, out] values under ``lax.scan``) such that
    ``w ≈ values * scale``."""

    def __init__(self, values: jax.Array, scale: jax.Array):
        self.values = values
        self.scale = scale

    @property
    def shape(self):
        return self.values.shape

    @property
    def ndim(self):
        return self.values.ndim

    @property
    def nbytes(self):
        return self.values.nbytes + self.scale.nbytes

    def dequantize(self, dtype=jnp.float32) -> jax.Array:
        return (self.values.astype(jnp.float32)
                * self.scale).astype(dtype)

    def __rmatmul__(self, x: jax.Array) -> jax.Array:
        # (x @ int8-as-activation-dtype) * scale: the cast and scale fuse
        # into the matmul; weight traffic from HBM stays int8.
        scale = self.scale.astype(x.dtype)
        if x.ndim == 1 and scale.ndim >= 2:
            # A 1-D x contributes no batch dim, so the product collapses
            # to [..., out] with the contracted slot GONE — drop its
            # size-1 slot from the kept-dims scale or broadcasting would
            # resurrect it ([out]*[1,out] → [1,out]; [L,out]*[L,1,out]
            # → [L,L,out]).
            scale = jnp.squeeze(scale, axis=-2)
        # Batched x keeps the kept-dims scale as-is: the contracted slot
        # broadcasts over x's batch dim ([B,out]*[1,out] is fine, and
        # stacked [L,in,out] values give [L,B,out]*[L,1,out] — squeezing
        # to [L,out] there would mis-align L with B).
        return (x @ self.values.astype(x.dtype)) * scale

    def __matmul__(self, other):  # pragma: no cover - weights are RHS
        return self.dequantize() @ other

    def tree_flatten(self):
        return (self.values, self.scale), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    def __repr__(self):
        return f"QTensor(shape={self.values.shape}, int8)"


def quantize(w: jax.Array, batch_dims: int = 0) -> QTensor:
    """Per-output-channel (last dim) symmetric int8.  ``batch_dims``
    leading axes are preserved in the scale — the stacked-layer ``[L,
    ...]`` weights need per-(layer, channel) scales so ``lax.scan`` can
    slice values and scale together."""
    wf = w.astype(jnp.float32)
    reduce_axes = tuple(range(batch_dims, w.ndim - 1))
    amax = jnp.max(jnp.abs(wf), axis=reduce_axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QTensor(q, scale)


def quantize_tree(params: dict, quant_keys: frozenset,
                  stacked_subtrees: frozenset,
                  stacked_batch_dims: dict | None = None) -> dict:
    """Quantize the named matmul-weight leaves of a parameter tree in
    one pass.  Keys under a subtree named in ``stacked_subtrees`` are
    stacked ``[L, ...]`` weights and get per-(layer, channel) scales;
    ``stacked_batch_dims`` overrides the preserved leading axes for
    specific stacked keys (e.g. MoE's ``[L, E, ...]`` expert weights
    need 2).  Works for any family whose forward consumes weights only
    via ``@`` (the QTensor overload boundary)."""
    overrides = stacked_batch_dims or {}

    def walk(tree, stacked: bool):
        out = {}
        for k, v in tree.items():
            if isinstance(v, dict):
                out[k] = walk(v, stacked=(k in stacked_subtrees))
            elif k in quant_keys:
                bd = overrides.get(k, 1) if stacked else 0
                out[k] = quantize(v, batch_dims=bd)
            else:
                out[k] = v
        return out
    return walk(params, stacked=False)


# Llama param-tree leaves worth quantizing: the big matmul weights.
# Norm scales are tiny; embed feeds `take`; biases don't exist.
_LLAMA_QUANT_KEYS = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"})

# T5: encoder attn (w*), decoder self (s*) + cross (c*) attn, the
# gated-GELU FFN, and the head.  Relative-bias tables feed `take` and
# stay full precision, like Llama's embedding.
_T5_QUANT_KEYS = frozenset(
    {"wq", "wk", "wv", "wo", "sq", "sk", "sv", "so",
     "cq", "ck", "cv", "co", "wi_0", "wi_1", "wo_ff", "lm_head"})


def quantize_llama(params: dict) -> dict:
    """Quantize a Llama/decode parameter tree in one pass; the result
    drops into ``llama_forward`` / ``prefill`` / ``decode_step`` /
    ``greedy_generate`` unchanged (weights are only used via ``@``)."""
    return quantize_tree(params, _LLAMA_QUANT_KEYS,
                         frozenset({"layers"}))


def quantize_moe(params: dict) -> dict:
    """Quantize a MoE parameter tree: attention + head like Llama, but
    the stacked expert FFN weights are ``[L, E, in, out]`` and need
    per-(layer, EXPERT, channel) scales — ``batch_dims=2`` — so
    ``jax.vmap`` over the expert axis maps values and scales in
    lockstep (a Llama-style [L, 1, 1, out] scale would both break the
    vmap axis sizes and silently share one scale across experts).  The
    f32 router stays full precision (routing is precision-critical)."""
    return quantize_tree(
        params, _LLAMA_QUANT_KEYS, frozenset({"layers"}),
        stacked_batch_dims={"w_gate": 2, "w_up": 2, "w_down": 2})


def quantize_t5(params: dict) -> dict:
    """Quantize a T5 encoder-decoder tree; drops into ``t5_encode`` /
    ``t5_greedy_generate`` unchanged — including the precomputed
    cross-K/V path (``enc_out @ ck`` traces through the QTensor
    overload like every other weight use)."""
    return quantize_tree(params, _T5_QUANT_KEYS,
                         frozenset({"encoder", "decoder"}))


def tree_nbytes(tree) -> int:
    return sum(leaf.nbytes for leaf in jax.tree.leaves(tree))

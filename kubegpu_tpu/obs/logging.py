"""Structured JSON logging (SURVEY.md §6 metrics/logging row).

The reference logged through glog verbosity levels only; KubeTPU emits
machine-parseable JSON lines — one object per event, stable keys
(``ts``, ``level``, ``component``, ``event`` + event fields) — so a log
pipeline (or grep + jq) can follow a pod through schedule → inject → run
without regex archaeology.

Built on the stdlib ``logging`` tree under the ``"kubetpu"`` root, so
embedders keep full control: attach handlers/levels per component, or
call :func:`configure` for the batteries-included JSON-lines-to-stderr
setup.  Loggers are cheap and process-global; components grab one with
``log = get_logger("scheduler")`` and emit ``log.info("schedule",
gang=g, slice=sid)``.
"""

from __future__ import annotations

import io
import json
import logging
import sys


# Library etiquette: without this, an unconfigured tree leaks WARNING+
# events to stderr as bare text via logging.lastResort (fields dropped).
# configure() attaches the real JSON handler when logging is opted into.
logging.getLogger("kubetpu").addHandler(logging.NullHandler())


class JsonFormatter(logging.Formatter):
    """One JSON object per record; event fields ride in ``record.fields``."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname.lower(),
            "component": record.name.removeprefix("kubetpu."),
            "event": record.getMessage(),
        }
        out.update(getattr(record, "fields", {}))
        if record.exc_info and record.exc_info[0] is not None:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


class StructuredLogger:
    """Thin wrapper giving ``log.info(event, **fields)`` ergonomics over a
    stdlib logger (stdlib wants printf args, not field dicts)."""

    def __init__(self, logger: logging.Logger):
        self._logger = logger

    def _emit(self, level: int, event: str, fields: dict) -> None:
        if self._logger.isEnabledFor(level):
            self._logger.log(level, event, extra={"fields": fields})

    def debug(self, event: str, **fields) -> None:
        self._emit(logging.DEBUG, event, fields)

    def info(self, event: str, **fields) -> None:
        self._emit(logging.INFO, event, fields)

    def warning(self, event: str, **fields) -> None:
        self._emit(logging.WARNING, event, fields)

    def error(self, event: str, **fields) -> None:
        self._emit(logging.ERROR, event, fields)


def get_logger(component: str) -> StructuredLogger:
    """Logger for one component (``scheduler``, ``crishim``, ...)."""
    return StructuredLogger(logging.getLogger(f"kubetpu.{component}"))


def configure(level: int = logging.INFO,
              stream: io.TextIOBase | None = None) -> logging.Handler:
    """JSON-lines handler on the ``kubetpu`` root (idempotent: replaces a
    previously configured one).  Returns the handler so tests/CLIs can
    detach or point it at a file."""
    root = logging.getLogger("kubetpu")
    for h in list(root.handlers):
        if getattr(h, "_kubetpu_json", False):
            root.removeHandler(h)
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(JsonFormatter())
    handler._kubetpu_json = True
    root.addHandler(handler)
    root.setLevel(level)
    root.propagate = False
    return handler


__all__ = ["JsonFormatter", "StructuredLogger", "get_logger", "configure"]

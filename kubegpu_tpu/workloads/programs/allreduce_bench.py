"""ICI allreduce microbenchmark — north-star metric #2 (BASELINE.md:
"ICI allreduce GB/s on allocated slice").

Gang-scheduled onto a slice, each worker psums a buffer across the global
mesh and measures achieved algorithmic bandwidth.  On real TPU the ring
rides ICI (placement quality = the scheduler's job); on the CPU simulation
it validates the full wiring (injection → jax.distributed → collective).

Prints one JSON line from worker 0.
"""

from __future__ import annotations

import json
import sys
import time


def main() -> int:
    from kubegpu_tpu.workloads.programs.distributed import init_from_env

    env = init_from_env()
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    mesh = Mesh(devs, ("dp",))
    n = len(devs)
    mib = 4.0  # MiB per device shard
    shard_elems = int(mib * (1 << 20) // 4)
    x = jnp.ones((jax.local_device_count(), shard_elems), jnp.float32) \
        * (env.worker_id + 1)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), x)

    @jax.jit
    def allreduce(a):
        return jax.lax.with_sharding_constraint(
            jnp.broadcast_to(a.sum(axis=0, keepdims=True), a.shape),
            NamedSharding(mesh, P("dp")))

    out = allreduce(arr)  # warmup + compile
    out.block_until_ready()
    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        out = allreduce(arr)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    # standard busBW convention: S = the reduced buffer each rank ends
    # with; a ring moves 2(n-1)/n * S per link
    payload_gib = shard_elems * 4 / (1 << 30)
    algo_gbs = (2 * (n - 1) / max(n, 1)) * payload_gib / dt
    if env.worker_id == 0:
        print(json.dumps({
            "metric": "allreduce_algo_bandwidth",
            "value": round(algo_gbs, 3),
            "unit": "GiB/s",
            "devices": n,
            "payload_gib": round(payload_gib, 4),
            "step_ms": round(dt * 1e3, 3),
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())

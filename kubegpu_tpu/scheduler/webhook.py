"""HTTP scheduler-extender service — the wire-level parity piece.

The reference's device-scheduler is an HTTP webhook kube-scheduler calls
per pod via its policy config (SURVEY.md §3 "Scheduler extender service":
``/filter`` predicate, ``/prioritize`` 0-10 scores, bind-time allocation
write-back per §4.2; §6 config row: ``extenders: [{urlPrefix,
filterVerb, prioritizeVerb, bindVerb, weight}]``).  This module serves
that API over the in-process :class:`DeviceScheduler`, speaking the k8s
``ExtenderArgs``/``ExtenderFilterResult``/``ExtenderBindingArgs`` JSON
shapes.

Request/response wire format (k8s.io/kubernetes/pkg/scheduler/api):

    POST <prefix>/filter      {"Pod": {...}, "NodeNames": [...]}
      → {"NodeNames": [...], "FailedNodes": {node: reason}, "Error": ""}
    POST <prefix>/prioritize  {"Pod": {...}, "NodeNames": [...]}
      → [{"Host": node, "Score": 0-10}, ...]   (HostPriorityList)
    POST <prefix>/bind        {"PodName", "PodNamespace", "PodUID", "Node"}
      → {"Error": ""}        (fills AllocateFrom + PATCHes the pod
                              annotation + binds — SURVEY.md §4.2)

The Pod document carries the same fields the annotation codec uses
(metadata.annotations for gang/mesh-axes/multislice, spec container
resources) — :func:`pod_from_doc` rebuilds the internal Pod.

What the wire verbs guarantee vs the in-process loop
----------------------------------------------------
A real kube-scheduler driving filter→prioritize→bind gets: per-node
feasibility/scoring, bind-time allocation write-back, namespace quota
gating, and GANG atomicity via hold-and-assume (all members' /filter
fail with "gang waiting (k/n)" until the gang is complete — the
scheduler's retry loop is the arrival barrier, as in the coscheduling
plugin — then one whole-gang placement steers every member).  What needs
the in-process ``run_once()`` loop instead: cross-gang FIFO fairness +
queue-seniority, priority preemption, conservative backfill, migration
defragmentation, and fault-driven eviction (a vanilla kube-scheduler
owns preemption itself and offers the extender no hook).  An abandoned
wire assumption (members never bound) expires after the gang grace and
its unbound chips are released.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubegpu_tpu.kubemeta.objects import (
    ContainerSpec,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequests,
)
from kubegpu_tpu.obs import get_logger
from kubegpu_tpu.scheduler.extender import DeviceScheduler

log = get_logger("webhook")


def pod_from_doc(doc: dict) -> Pod:
    """k8s Pod JSON → internal Pod (the fields the scheduler reads)."""
    meta = doc.get("metadata") or {}
    spec = doc.get("spec") or {}
    containers = []
    for c in spec.get("containers") or []:
        requests = ((c.get("resources") or {}).get("requests")
                    or (c.get("resources") or {}).get("limits") or {})
        containers.append(ContainerSpec(
            name=c.get("name", "main"),
            command=[str(x) for x in c.get("command") or []],
            env={e["name"]: str(e.get("value", ""))
                 for e in c.get("env") or []},
            resources=ResourceRequests.from_dict(
                {k: float(v) for k, v in requests.items()
                 if k.startswith("kubetpu.io/")}),
        ))
    return Pod(
        metadata=ObjectMeta(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            labels=dict(meta.get("labels") or {}),
            annotations=dict(meta.get("annotations") or {}),
        ),
        spec=PodSpec(containers=containers,
                     priority=int(spec.get("priority", 0))),
    )


def pod_to_doc(pod: Pod) -> dict:
    """Internal Pod → k8s Pod JSON (round-trip for tests/clients)."""
    return {
        "metadata": {
            "name": pod.metadata.name,
            "namespace": pod.metadata.namespace,
            "labels": dict(pod.metadata.labels),
            "annotations": dict(pod.metadata.annotations),
        },
        "spec": {
            "priority": pod.spec.priority,
            "containers": [
                {
                    "name": c.name,
                    "command": list(c.command),
                    "env": [{"name": k, "value": v}
                            for k, v in c.env.items()],
                    "resources": {"requests": {
                        k: str(v) for k, v in c.resources.to_dict().items()
                    }},
                }
                for c in pod.spec.containers
            ],
        },
    }


class ExtenderService:
    """The verb layer: ExtenderArgs JSON in, extender results out."""

    def __init__(self, scheduler: DeviceScheduler):
        self.scheduler = scheduler

    def filter(self, args: dict) -> dict:
        pod = pod_from_doc(args.get("Pod") or {})
        node_names = list(args.get("NodeNames") or [])
        feasible, reasons = self.scheduler.filter(pod, node_names)
        return {"NodeNames": feasible, "FailedNodes": reasons, "Error": ""}

    def prioritize(self, args: dict) -> list[dict]:
        pod = pod_from_doc(args.get("Pod") or {})
        node_names = list(args.get("NodeNames") or [])
        scores = self.scheduler.prioritize(pod, node_names)
        return [{"Host": n, "Score": int(round(scores.get(n, 0.0)))}
                for n in node_names]

    def bind(self, args: dict) -> dict:
        """ExtenderBindingArgs → ExtenderBindingResult."""
        err = self.scheduler.bind(
            str(args.get("PodName") or ""),
            str(args.get("Node") or ""),
            namespace=str(args.get("PodNamespace") or "default"))
        return {"Error": err or ""}


class ExtenderHTTPServer:
    """ThreadingHTTPServer wrapper: start() binds and serves in a daemon
    thread, close() shuts down.  ``prefix`` mirrors the kube-scheduler
    policy-config ``urlPrefix``."""

    def __init__(self, scheduler: DeviceScheduler, host: str = "127.0.0.1",
                 port: int = 0, prefix: str = "/kubetpu"):
        service = ExtenderService(scheduler)
        prefix = prefix.rstrip("/")

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet; we log structured below
                pass

            def do_GET(self) -> None:
                if self.path.split("?", 1)[0] != "/metrics":
                    self.send_error(404, f"unknown path {self.path}")
                    return
                # Prometheus scrape surface: the schedule-latency
                # summary here IS north-star metric #1
                reg = getattr(scheduler, "metrics", None)
                body = (reg.to_prometheus() if reg is not None
                        else "").encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self) -> None:
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    args = json.loads(self.rfile.read(n) or b"{}")
                    if self.path == f"{prefix}/filter":
                        out = service.filter(args)
                    elif self.path == f"{prefix}/prioritize":
                        out = service.prioritize(args)
                    elif self.path == f"{prefix}/bind":
                        out = service.bind(args)
                    else:
                        self.send_error(404, f"unknown verb {self.path}")
                        return
                except Exception as e:
                    log.error("verb_failed", path=self.path, error=str(e))
                    if self.path == f"{prefix}/filter":
                        # filter's contract carries an Error field
                        out = {"NodeNames": [], "FailedNodes": {},
                               "Error": str(e)}
                    elif self.path == f"{prefix}/bind":
                        out = {"Error": str(e)}
                    else:
                        # prioritize's contract is a bare HostPriorityList
                        # (no Error slot) — signal failure at HTTP level
                        self.send_error(500, str(e))
                        return
                body = json.dumps(out).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ExtenderHTTPServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        log.info("listening", address=self.address)
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


def policy_config(extender_url: str, weight: int = 10) -> dict:
    """The kube-scheduler policy-config stanza registering this extender
    (SURVEY.md §6 config row) — what a real deployment drops into
    ``--policy-config-file``."""
    return {
        "kind": "Policy",
        "apiVersion": "v1",
        "extenders": [{
            "urlPrefix": f"{extender_url}/kubetpu",
            "filterVerb": "filter",
            "prioritizeVerb": "prioritize",
            "bindVerb": "bind",
            "weight": weight,
            "enableHttps": False,
            # nodeCacheCapable=true ⇒ kube-scheduler sends/accepts
            # NodeNames (the forms this service speaks) instead of full
            # Node objects
            "nodeCacheCapable": True,
        }],
    }

"""Gang-schedule latency benchmark (north-star metric #1) — package home.

Drives the real scheduler end-to-end on a simulated multi-slice cluster
(2× v5e-64 + v4-8) with a churning stream of mixed gang workloads — the
same path BASELINE.md's "gang-schedule p50 latency" names.  The repo-root
``bench.py`` (the driver's entry point) and ``kubetpu bench`` both call
:func:`run_bench` here, so the verb works from an installed package too.

``vs_baseline`` compares against the stand-in baseline BASELINE.md defines
(the reference publishes no numbers): 50 ms p50, the figure recorded from
this framework's round-1 run.  >1.0 means faster than baseline.
"""

from __future__ import annotations

import random

BASELINE_P50_MS = 50.0


def run_bench(n_gangs: int = 60, seed: int = 0) -> dict:
    from kubegpu_tpu.cluster import SimCluster, tpu_pod
    from kubegpu_tpu.kubemeta import GangSpec, NotFound, PodPhase

    rng = random.Random(seed)
    cl = SimCluster(["v5e-64", "v5e-64", "v4-8"])
    # mixed workload: DP gangs, tp-heavy llama-style gangs, single chips,
    # fractional co-tenants — with completion churn so the allocator works
    # against fragmentation, not an empty cluster.
    shapes = [
        dict(pods=4, chips=1, axes={"dp": 4}),
        dict(pods=4, chips=4, axes={"dp": 4, "tp": 4}),
        dict(pods=16, chips=4, axes={"dp": 4, "tp": 16}),
        dict(pods=8, chips=4, axes={"dp": 2, "tp": 16}),
        dict(pods=1, chips=1, axes=None),
        dict(pods=1, chips=4, axes={"dp": 1, "tp": 4}),
        dict(pods=1, chips=0, axes=None, millitpu=500),
    ]

    def finish_one(live_list):
        """Complete one random live gang: delete its pods → watch event →
        the scheduler releases its slice."""
        for name in live_list.pop(rng.randrange(len(live_list))):
            try:
                cl.api.delete("Pod", name)
            except NotFound:
                pass

    def gang_placed(names):
        return all(
            cl.api.get("Pod", n).status.phase != PodPhase.PENDING
            for n in names)

    live: list[list[str]] = []
    for g in range(n_gangs):
        spec = rng.choice(shapes)
        names = []
        if spec.get("millitpu"):
            names.append(f"frac-{g}")
            cl.submit(tpu_pod(f"frac-{g}", millitpu=spec["millitpu"],
                              command=["x"]))
        elif spec["pods"] == 1:
            names.append(f"pod-{g}")
            cl.submit(tpu_pod(f"pod-{g}", chips=spec["chips"],
                              mesh_axes=spec["axes"], command=["x"]))
        else:
            for i in range(spec["pods"]):
                name = f"gang{g}-{i}"
                names.append(name)
                cl.submit(tpu_pod(
                    name, chips=spec["chips"],
                    gang=GangSpec(name=f"gang{g}", size=spec["pods"],
                                  index=i),
                    mesh_axes=spec["axes"], command=["x"]))
        cl.step()
        # queue-drain model: if the gang didn't fit, complete live gangs
        # one at a time until it does — the allocator always works
        # against a fragmented, partially-occupied cluster, and every
        # successful placement latency lands in the histogram.
        while not gang_placed(names) and live:
            finish_one(live)
            cl.step()
        if gang_placed(names):
            live.append(names)
        # background churn keeps occupancy realistic (~40% completion)
        if len(live) > 4 and rng.random() < 0.4:
            finish_one(live)
    cl.reap()
    snap = cl.metrics.snapshot()
    hist = snap["histograms"].get("schedule_latency_ms", {})
    loc = snap["histograms"].get("allocation_locality", {})
    p50 = hist.get("p50", 0.0)
    return {
        "metric": "gang_schedule_p50_latency",
        "value": round(p50, 3),
        "unit": "ms",
        # 0.0 (not inf) when nothing scheduled: a broken run must not
        # read as a record win
        "vs_baseline": round(BASELINE_P50_MS / p50, 2) if p50 > 0 else 0.0,
        "details": {
            "p90_ms": round(hist.get("p90", 0.0), 3),
            "p99_ms": round(hist.get("p99", 0.0), 3),
            # the histogram covers EVERY decision, failed ones included —
            # the expensive infeasible searches are in the percentiles
            "decisions": hist.get("count", 0),
            "gangs_scheduled": snap["counters"].get("gangs_scheduled", 0),
            "decisions_failed": snap["counters"].get("gangs_failed", 0),
            "unschedulable": snap["counters"].get(
                "schedule_unschedulable", 0),
            "mean_allocation_locality": round(loc.get("mean", 0.0), 4),
            "baseline_p50_ms": BASELINE_P50_MS,
        },
    }

"""Weight-only int8 quantization for serving (TPU-native addition).

Autoregressive decode is HBM-bandwidth-bound: every generated token
streams the full weight set through the MXU, so halving weight bytes
(bf16 → int8 + per-channel scales) is a direct ~2x on the decode
bottleneck.  Classic symmetric per-output-channel scheme (AWQ/GPTQ-free
round-to-nearest — adequate at 8 bits).

Design: :class:`QTensor` is a pytree-registered (int8 values, f32
per-channel scale) pair whose ``@`` overloads dequantize lazily inside
the jitted graph — ``x @ qw`` traces as ``(x @ values.astype(x.dtype)) *
scale``, which XLA fuses into the matmul epilogue.  Because the model
code only ever uses weights via ``@``, :func:`quantize_params` can swap
leaves in place and the existing Llama forward / KV-cache decode run
UNCHANGED on a quantized tree (norms, embeddings, and biases stay in
full precision; embedding stays because it is consumed by ``take``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class QTensor:
    """Symmetric per-output-channel int8 weight: ``values`` [..., out]
    int8, ``scale`` f32 broadcastable against ``values`` (reduced axes
    kept as size-1, so stacked [L, 1, out] scales slice in lockstep with
    [L, in, out] values under ``lax.scan``) such that
    ``w ≈ values * scale``."""

    def __init__(self, values: jax.Array, scale: jax.Array):
        self.values = values
        self.scale = scale

    @property
    def shape(self):
        return self.values.shape

    @property
    def ndim(self):
        return self.values.ndim

    @property
    def nbytes(self):
        return self.values.nbytes + self.scale.nbytes

    def dequantize(self, dtype=jnp.float32) -> jax.Array:
        return (self.values.astype(jnp.float32)
                * self.scale).astype(dtype)

    def __rmatmul__(self, x: jax.Array) -> jax.Array:
        # (x @ int8-as-activation-dtype) * scale: the cast and scale fuse
        # into the matmul; weight traffic from HBM stays int8.
        scale = self.scale.astype(x.dtype)
        if x.ndim == 1 and scale.ndim >= 2:
            # A 1-D x contributes no batch dim, so the product collapses
            # to [..., out] with the contracted slot GONE — drop its
            # size-1 slot from the kept-dims scale or broadcasting would
            # resurrect it ([out]*[1,out] → [1,out]; [L,out]*[L,1,out]
            # → [L,L,out]).
            scale = jnp.squeeze(scale, axis=-2)
        # Batched x keeps the kept-dims scale as-is: the contracted slot
        # broadcasts over x's batch dim ([B,out]*[1,out] is fine, and
        # stacked [L,in,out] values give [L,B,out]*[L,1,out] — squeezing
        # to [L,out] there would mis-align L with B).
        return (x @ self.values.astype(x.dtype)) * scale

    def __matmul__(self, other):  # pragma: no cover - weights are RHS
        return self.dequantize() @ other

    def tree_flatten(self):
        return (self.values, self.scale), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    def __repr__(self):
        return f"QTensor(shape={self.values.shape}, int8)"


def quantize(w: jax.Array, batch_dims: int = 0) -> QTensor:
    """Per-output-channel (last dim) symmetric int8.  ``batch_dims``
    leading axes are preserved in the scale — the stacked-layer ``[L,
    ...]`` weights need per-(layer, channel) scales so ``lax.scan`` can
    slice values and scale together."""
    wf = w.astype(jnp.float32)
    reduce_axes = tuple(range(batch_dims, w.ndim - 1))
    amax = jnp.max(jnp.abs(wf), axis=reduce_axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QTensor(q, scale)


# Llama param-tree leaves worth quantizing: the big matmul weights.
# Norm scales are tiny; embed feeds `take`; biases don't exist.
_LLAMA_QUANT_KEYS = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"})


def quantize_llama(params: dict) -> dict:
    """Quantize a Llama/decode parameter tree in one pass; the result
    drops into ``llama_forward`` / ``prefill`` / ``decode_step`` /
    ``greedy_generate`` unchanged (weights are only used via ``@``)."""
    def walk(tree, stacked: bool):
        out = {}
        for k, v in tree.items():
            if isinstance(v, dict):
                # the "layers" subtree holds stacked [L, ...] weights
                out[k] = walk(v, stacked=(k == "layers"))
            elif k in _LLAMA_QUANT_KEYS:
                out[k] = quantize(v, batch_dims=1 if stacked else 0)
            else:
                out[k] = v
        return out
    return walk(params, stacked=False)


def tree_nbytes(tree) -> int:
    return sum(leaf.nbytes for leaf in jax.tree.leaves(tree))

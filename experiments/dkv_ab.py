"""Interleaved A/B of grouped-dkv q-block geometries (r5 item #2).

Compiles every variant FIRST (the tunnel's remote-compile helper fails
under a busy device queue), then alternates timing bursts A/B/A/B and
reports per-variant medians — cross-window tunnel variance measured
45% on these sub-3ms kernels, so only interleaved same-window bursts
can rank geometries."""

import importlib
import statistics
import sys
import time

sys.path.insert(0, "/root/repo")

import jax                                      # noqa: E402
import jax.numpy as jnp                         # noqa: E402
import numpy as np                              # noqa: E402

fa = importlib.import_module("kubegpu_tpu.ops.flash_attention")
RAW_BWD = fa.flash_attention_bwd.__wrapped__
_ORIG_CAP = fa.DKV_GROUPED_BQ_CAP
# NB: unlike bwd_profile.py this harness deliberately skips the
# _fetch_rtt_s compensation — the fetch overhead is CONSTANT across
# interleaved variants, so rankings hold but absolute ms here are
# inflated vs benchmark.py's numbers.

B, HQ, HKV, T, D = 4, 16, 4, 2048, 128
DT = jnp.bfloat16
ITERS = 60
ROUNDS = 5


def fetch(x):
    return float(np.asarray(jax.device_get(jnp.ravel(x)[0])))


def main():
    key = jax.random.PRNGKey(0)
    kq, kk, kv, kg = jax.random.split(key, 4)
    q = jax.random.normal(kq, (B, HQ, T, D), DT)
    k = jax.random.normal(kk, (B, HKV, T, D), DT)
    v = jax.random.normal(kv, (B, HKV, T, D), DT)
    g = jax.random.normal(kg, (B, HQ, T, D), DT)
    out, lse = jax.jit(
        lambda: fa.flash_attention(q, k, v, return_lse=True))()

    variants = {}
    specs = [("bq256", 256, 512), ("bq512", 512, 512),
             ("bq128", 128, 512), ("bq256bk256", 256, 256)]
    for name, cap, bk in specs:
        fa.DKV_GROUPED_BQ_CAP = cap

        def mk(bk=bk):
            def run(g_):
                dq, dk, dv = RAW_BWD(q, k, v, out, lse, g_, True,
                                     512, bk, False)
                del dq
                return (g_ + (dk[0, 0, 0, 0]
                              + dv[0, 0, 0, 0]).astype(g_.dtype)
                        * jnp.bfloat16(1e-8))
            return jax.jit(run)
        try:
            fn = mk()
            fetch(fn(g))   # compile now, device quiet
            variants[name] = fn
            print(f"compiled {name}", flush=True)
        except Exception as e:
            print(f"{name}: COMPILE FAILED {str(e)[:120]}", flush=True)
        finally:
            fa.DKV_GROUPED_BQ_CAP = _ORIG_CAP

    times = {n: [] for n in variants}
    for r in range(ROUNDS):
        for name, fn in variants.items():
            st = g
            t0 = time.perf_counter()
            for _ in range(ITERS):
                st = fn(st)
            fetch(st)
            times[name].append((time.perf_counter() - t0) / ITERS)
    for name, ts in times.items():
        med = statistics.median(ts)
        print(f"dkv {name}: median {med*1e3:7.3f} ms  "
              f"(all: {[round(t*1e3, 3) for t in ts]})", flush=True)


if __name__ == "__main__":
    main()

"""KubeTPU benchmark entry point: gang-schedule p50 latency + headlines.

Output contract (VERDICT r4 next-item #1 — the driver's capture window
is a ~2000-char stdout tail plus a parse of what it finds there, and for
two rounds the one giant JSON line truncated mid-document, losing the
flagship MFU/decode numbers from the record):

  stdout line 1: the FULL bench document (one JSON line, large)
  stdout line 2 (FINAL): a compact headline summary, < ~1500 bytes,
      guaranteed to sit whole inside the tail window and to parse on
      its own — metric/p50/vs_baseline, train MFU, flash speedup,
      decode ladder, continuous-batching A/B, PLD, scheduler scale.

The full document is also written to BENCH_DETAILS.json next to this
file.  The benchmark itself lives in kubegpu_tpu/benchmark.py (shared
with the ``kubetpu bench`` CLI verb).

Strict-mode fence (VERDICT r4 next-item #3): the bench exports
KUBETPU_REQUIRE_PALLAS=1 so any silent hot-path fallback (pallas→XLA
attention, paged→dense engine) ABORTS the run instead of recording a
plausible-but-degraded number — the r1-r3 MFU misattribution class.
Set KUBETPU_REQUIRE_PALLAS=0 explicitly to run permissive.
"""

from __future__ import annotations

import json
import os
import sys

if __name__ == "__main__":
    repo = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, repo)
    os.environ.setdefault("KUBETPU_REQUIRE_PALLAS", "1")
    from kubegpu_tpu.benchmark import run_full_bench, summarize_bench
    n = int(os.environ.get("BENCH_GANGS", "60"))
    out = run_full_bench(n_gangs=n)
    full = json.dumps(out)
    try:
        with open(os.path.join(repo, "BENCH_DETAILS.json"), "w") as f:
            f.write(full + "\n")
    except OSError:
        pass   # a read-only checkout must not sink the record
    print(full)
    s = summarize_bench(out)
    summary = json.dumps(s)
    if len(summary) > 1800:   # belt-and-braces: never outgrow the tail
        summary = json.dumps({
            "metric": out.get("metric"), "value": out.get("value"),
            "unit": out.get("unit"),
            "vs_baseline": out.get("vs_baseline"),
            "mfu": s.get("mfu"),
            "summary_overflow": len(summary)})
    print(summary, flush=True)

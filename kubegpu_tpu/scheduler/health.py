"""Failure detection + elastic recovery (SURVEY.md §6).

The reference had no first-party failure handling — it leaned on
Kubernetes-native behavior (node NotReady eviction, pod restart policies)
and on its annotations-as-truth design making the scheduler restartable.
This controller is the TPU-native equivalent SURVEY.md §6 specifies: a
chip/link marked bad (or a host going NotReady) makes the slices containing
it infeasible, and any *committed gang* touching the fault is evicted and
requeued so the scheduler re-places it on a fresh healthy sub-mesh.

Gang semantics drive the whole-gang eviction: a JAX multi-host program is
all-or-nothing (``jax.distributed`` workers must restart together to form a
new coordination barrier), so losing one worker's chips means evicting every
member — partial recovery is impossible by construction.

Eviction here collapses two k8s actors into one step, the same way the rest
of the simulated control plane does: the *eviction* (delete) and the *Job /
StatefulSet controller* recreating an identical pending pod.  The recreated
pod keeps its name, spec, gang membership, and mesh-axes hint; it loses its
binding and allocation annotation, so the next scheduling pass treats the
gang as brand new.  Workload-side resume is the checkpoint story
(workloads/ Orbax-style checkpointing; see tests/test_recovery.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubegpu_tpu.allocator.gang import GangAssignment, SliceState
from kubegpu_tpu.kubemeta import FakeApiServer, Pod, PodPhase
from kubegpu_tpu.kubemeta.controlplane import WatchEvent
from kubegpu_tpu.obs import MetricsRegistry, ScheduleTrace
from kubegpu_tpu.scheduler.extender import DeviceScheduler


@dataclass
class RecoveryResult:
    evicted_gangs: dict[str, str] = field(default_factory=dict)  # gang → why
    requeued_pods: list[str] = field(default_factory=list)


class FaultRecoveryController:
    """Watches Node health, detects broken committed gangs, evicts+requeues.

    Runs as part of the control-plane tick (SimCluster.step), before the
    scheduling pass, so a fault observed at tick T has its gangs back in the
    queue for the same tick's scheduling decision.
    """

    def __init__(self, api: FakeApiServer, scheduler: DeviceScheduler,
                 metrics: MetricsRegistry | None = None,
                 trace: ScheduleTrace | None = None):
        self.api = api
        self.scheduler = scheduler
        self.metrics = metrics or scheduler.metrics
        self.trace = trace or scheduler.trace
        self._dirty = True  # first pass always inspects
        self._degraded: set[str] = set()  # gangs left on a bad link
        self._unsub = api.watch(self._on_event)

    def close(self) -> None:
        self._unsub()

    def _on_event(self, ev: WatchEvent) -> None:
        # Any node change (readiness flip, re-advertisement after a fault
        # injection, node add/remove) can change slice health.  Pod churn
        # matters only while a degraded gang waits for capacity to free up
        # (a completing pod may open the better footprint it needs).
        if ev.kind == "Node" or (ev.kind == "Pod" and self._degraded):
            self._dirty = True

    # ------------------------------------------------------------------

    def run_once(self) -> RecoveryResult:
        result = RecoveryResult()
        if not self._dirty:
            return result
        self._dirty = False
        # Re-sync slice states from annotation truth: not-ready nodes drop
        # out (their coords leave `available`), re-advertised health lands
        # in `unhealthy`/`bad_links`.
        self.scheduler.sync()
        self._degraded.clear()
        for gang, asg in list(self.scheduler._committed.items()):
            broken = self._broken_reason(asg)
            if broken is None:
                continue
            reason, kind = broken
            if kind == "link" and not self._better_placement_exists(gang, asg):
                # The dead link degrades this gang, but every alternative is
                # the same footprint (or nothing) — evicting would thrash.
                # Tracked so pod churn re-triggers this evaluation.
                self._degraded.add(gang)
                self.trace.record("degraded", gang=gang,
                                  detail={"reason": reason})
                continue
            self._evict_gang(gang, reason, result)
        if result.evicted_gangs:
            # Eviction released chips; the queue sees the pods next pass.
            self.metrics.inc("gangs_evicted", len(result.evicted_gangs))
        return result

    # ------------------------------------------------------------------

    def _broken_reason(self, asg: GangAssignment) -> tuple[str, str] | None:
        """(human reason, kind) — kind 'hard' (chips gone) or 'link'
        (degraded: chips fine, an interior ICI link died).  Every slice
        the gang touches is inspected, and a 'hard' fault anywhere wins
        over a 'link' fault elsewhere — a multislice gang with one slice
        merely degraded and another DEAD must evict, not park."""
        link_found: tuple[str, str] | None = None
        for sid in asg.slice_ids:
            st = self.scheduler.slices.get(sid)
            if st is None:
                return f"slice {sid} disappeared (all hosts down)", "hard"
            coords = [ch.coord for p in asg.pods
                      if asg.pod_slice(p) == sid for ch in p.chips]
            coord_set = set(coords)
            for c in coords:
                if c not in st.available:
                    return (f"chip {c} no longer advertised (host down)",
                            "hard")
                if c in st.unhealthy:
                    return f"chip {c} marked unhealthy", "hard"
            # A dead ICI link strictly inside the allocation footprint
            # breaks the gang's collectives (rings detour → catastrophic
            # slowdown on a torus) — re-place if anywhere better exists.
            if link_found is None:
                for a, b in st.bad_links:
                    if a in coord_set and b in coord_set:
                        link_found = (
                            f"ICI link {a}–{b} failed inside allocation",
                            "link")
                        break
        return link_found

    def _better_placement_exists(self, gang: str,
                                 asg: GangAssignment) -> bool:
        """Trial re-placement with this gang's chips freed: is there an
        assignment on a different footprint?  (Scoring already penalizes
        bad links, so a different footprint means a better one.)

        The trial request is rebuilt from the committed assignment itself
        — not from live member pods — so partially-completed or
        already-garbage-collected members can't skew the shape."""
        from kubegpu_tpu.allocator import GangRequest
        from kubegpu_tpu.kubemeta import pod_mesh_axes, pod_multislice

        if not asg.pods or not asg.pods[0].chips:
            return False
        chips_per_pod = len(asg.pods[0].chips)
        members = self._gang_member_pods(gang)
        axes = pod_mesh_axes(members[0]) if members else None
        try:
            req = GangRequest(
                gang_name=gang, num_pods=len(asg.pods),
                chips_per_pod=chips_per_pod,
                # same HBM floor the real re-schedule will enforce — an
                # 'alternative' on low-HBM chips would evict toward a
                # placement _request_for_gang then rejects (stranding)
                hbm_gib_per_chip=max(
                    (p.spec.max_hbm_gib for p in members), default=0.0),
                mesh_axes=self.scheduler._sane_axes(
                    axes, len(asg.pods) * chips_per_pod),
                # a multislice gang's alternative may also be multislice
                allow_multislice=bool(members)
                and pod_multislice(members[0]))
        except ValueError:
            return False
        alloc = self.scheduler.allocator
        slices = self.scheduler.slices
        alloc.rollback(slices, asg)
        try:
            alt = alloc.find_assignment(list(slices.values()), req)
        finally:
            alloc.commit(slices, asg)
        if alt is None:
            return False
        # coords are slice-local, so compare (slice, coord) pairs — an
        # untagged union would conflate colliding coords across slices of
        # a multislice gang
        cur = {(asg.pod_slice(p), ch.coord)
               for p in asg.pods for ch in p.chips}
        new = {(alt.pod_slice(p), ch.coord)
               for p in alt.pods for ch in p.chips}
        return new != cur

    def _gang_member_pods(self, gang: str) -> list[Pod]:
        return self.scheduler.gang_member_pods(gang)

    def _evict_gang(self, gang: str, reason: str,
                    result: RecoveryResult) -> None:
        # Delete-first + recreate-pending lives on the scheduler (shared
        # with priority preemption).
        result.requeued_pods.extend(self.scheduler.evict_gang(gang, reason))
        result.evicted_gangs[gang] = reason

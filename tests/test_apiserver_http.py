"""HTTP apiserver façade (SURVEY.md §2 key property on a real wire):
every scheduler↔agent coordination path must work with the control plane
behind HTTP, and the node daemon must run against it out-of-process."""

import threading
import time

import pytest

from kubegpu_tpu.cluster import tpu_pod
from kubegpu_tpu.kubemeta import (
    Conflict,
    FakeApiServer,
    GangSpec,
    Node,
    NotFound,
    ObjectMeta,
    PodPhase,
    Quota,
    QuotaSpec,
)
from kubegpu_tpu.kubemeta.apiserver_http import ApiServerHTTP, HttpApiClient
from kubegpu_tpu.kubemeta.codec import pod_gang_spec, set_pod_gang
from kubegpu_tpu.kubemeta.serialize import from_doc, to_doc


@pytest.fixture
def served():
    api = FakeApiServer()
    srv = ApiServerHTTP(api).start()
    client = HttpApiClient(srv.address)
    yield api, srv, client
    client.close()
    srv.close()


class TestSerialize:
    def test_pod_roundtrip(self):
        pod = tpu_pod("p", chips=2, command=["python", "-m", "x"],
                      env={"A": "1"}, priority=3, namespace="team-a",
                      gang=GangSpec(name="g", size=4, index=1),
                      mesh_axes={"dp": 2, "tp": 2}, hbm_gib=8.0)
        pod.spec.node_name = "node-0"
        pod.status.phase = PodPhase.RUNNING
        pod.status.exit_code = None
        back = from_doc("Pod", to_doc("Pod", pod))
        assert back.metadata.name == "p"
        assert back.metadata.namespace == "team-a"
        assert back.metadata.uid == pod.metadata.uid
        assert back.spec.node_name == "node-0"
        assert back.spec.priority == 3
        assert back.status.phase == PodPhase.RUNNING
        c = back.spec.containers[0]
        assert c.resources.tpu_chips == 2
        assert c.resources.hbm_gib == 8.0
        assert c.command == ["python", "-m", "x"]
        assert c.env == {"A": "1"}
        # annotation payloads (gang etc.) survive verbatim
        assert pod_gang_spec(back) == GangSpec(name="g", size=4, index=1)

    def test_node_and_quota_roundtrip(self):
        node = Node(metadata=ObjectMeta(name="n0",
                                        annotations={"k": "v"}))
        node.status.ready = False
        back = from_doc("Node", to_doc("Node", node))
        assert back.name == "n0" and back.status.ready is False
        assert back.metadata.annotations == {"k": "v"}
        q = Quota(metadata=ObjectMeta(name="quota", namespace="t"),
                  spec=QuotaSpec(tpu_chips=8, millitpu=None))
        back = from_doc("Quota", to_doc("Quota", q))
        assert back.spec.tpu_chips == 8 and back.spec.millitpu is None


class TestRestSurface:
    def test_crud_roundtrip(self, served):
        api, srv, client = served
        client.create("Pod", tpu_pod("p", chips=1, command=["x"]))
        got = client.get("Pod", "p")
        assert got.name == "p"
        assert api.get("Pod", "p").metadata.uid == got.metadata.uid
        with pytest.raises(Conflict):
            client.create("Pod", tpu_pod("p", chips=1, command=["x"]))
        client.delete("Pod", "p")
        with pytest.raises(NotFound):
            client.get("Pod", "p")

    def test_field_selectors_over_wire(self, served):
        api, srv, client = served
        client.create("Pod", tpu_pod("a", chips=1, command=["x"]))
        client.create("Pod", tpu_pod("b", chips=1, command=["x"],
                                     namespace="other"))
        client.bind_pod("a", "node-0")
        assert [p.name for p in client.list(
            "Pod", node_name="node-0", phase=PodPhase.SCHEDULED)] == ["a"]
        assert [p.name for p in client.list(
            "Pod", namespace="other")] == ["b"]
        assert client.list("Pod", node_name="nope") == []

    def test_annotation_patch_with_null_delete(self, served):
        api, srv, client = served
        client.create("Pod", tpu_pod("p", chips=0, command=["x"]))
        client.patch_annotations("Pod", "p", {"x": "1", "y": "2"})
        client.patch_annotations("Pod", "p", {"x": None})
        assert client.get("Pod", "p").metadata.annotations.get("y") == "2"
        assert "x" not in client.get("Pod", "p").metadata.annotations

    def test_status_subresource_incarnation_safe(self, served):
        api, srv, client = served
        client.create("Pod", tpu_pod("p", chips=0, command=["x"]))
        uid = client.get("Pod", "p").metadata.uid
        client.set_pod_phase("p", PodPhase.RUNNING, expect_uid=uid)
        assert client.get("Pod", "p").status.phase == PodPhase.RUNNING
        with pytest.raises(NotFound, match="recreated"):
            client.set_pod_phase("p", PodPhase.FAILED,
                                 expect_uid="uid-of-the-dead")

    def test_node_ready_subresource(self, served):
        api, srv, client = served
        client.create("Node", Node(metadata=ObjectMeta(name="n0")))
        client.set_node_ready("n0", False)
        assert api.get("Node", "n0").status.ready is False

    def test_update_optimistic_concurrency(self, served):
        api, srv, client = served
        client.create("Pod", tpu_pod("p", chips=0, command=["x"]))
        pod = client.get("Pod", "p")
        pod.spec.priority = 9
        client.update("Pod", pod)
        stale = pod  # rv now behind
        stale.spec.priority = 1
        with pytest.raises(Conflict):
            client.update("Pod", stale)

    def test_watch_long_poll_no_history_replay(self, served):
        api, srv, client = served
        client.create("Pod", tpu_pod("old", chips=0, command=["x"]))
        seen: list[tuple[str, str]] = []
        done = threading.Event()

        def cb(ev):
            seen.append((ev.type, ev.obj.metadata.name))
            done.set()

        unsub = client.watch(cb)
        time.sleep(0.15)  # let the tail handshake land
        client.create("Pod", tpu_pod("fresh", chips=0, command=["x"]))
        assert done.wait(5.0)
        unsub()
        assert ("ADDED", "fresh") in seen
        # the pre-subscribe object was NOT replayed
        assert all(name != "old" for _, name in seen)

    def test_watch_resubscribe_after_full_unsubscribe(self, served):
        """Regression (review): a new watcher registered while the old
        poll thread is still winding down after the last unsubscribe
        must still get events (each generation has its own stop flag)."""
        api, srv, client = served
        unsub1 = client.watch(lambda ev: None)
        unsub1()   # old thread may still be inside its long-poll
        got = threading.Event()
        unsub2 = client.watch(lambda ev: got.set())
        time.sleep(0.15)
        client.create("Pod", tpu_pod("after", chips=0, command=["x"]))
        assert got.wait(5.0), "re-subscribed watcher starved of events"
        unsub2()


class TestOutOfProcessAgent:
    """The crishim daemon shape: NodeAgent + CriServer talking to the
    control plane ONLY via HttpApiClient, scheduler in the main process
    — the reference's deployment topology (SURVEY.md §4)."""

    def _cluster_with_remote_agent(self):
        from kubegpu_tpu.allocator import GangAllocator
        from kubegpu_tpu.crishim.agent import NodeAgent
        from kubegpu_tpu.crishim.criserver import CriServer, RemoteCriShim
        from kubegpu_tpu.crishim.runtime import FakeRuntime
        from kubegpu_tpu.scheduler import DeviceScheduler
        from kubegpu_tpu.tpuplugin import MockBackend

        api = FakeApiServer()
        srv = ApiServerHTTP(api).start()
        client = HttpApiClient(srv.address)
        backend = MockBackend("v4-8")
        runtime = FakeRuntime()
        cri = CriServer(client, backend, backend.discover().node_name,
                        runtime).start()
        agent = NodeAgent(client, backend, runtime,
                          shim=RemoteCriShim(cri.socket_path))
        agent.register()
        sched = DeviceScheduler(api, allocator=GangAllocator())
        return api, srv, client, cri, agent, sched, runtime

    def test_full_path_over_http_and_socket(self):
        api, srv, client, cri, agent, sched, runtime = \
            self._cluster_with_remote_agent()
        try:
            # node registered THROUGH the HTTP wire is visible in-process
            assert api.get("Node", agent.node_name) is not None
            api.create("Pod", tpu_pod("job", chips=2, command=["x"]))
            res = sched.run_once()
            assert res.scheduled == ["job"]
            started = agent.run_once()   # HTTP list → CRI socket create
            assert len(started) == 1
            assert len(started[0].env["TPU_VISIBLE_CHIPS"].split(",")) == 2
            assert agent.reap(timeout=2) == {"job": 0}
            assert api.get("Pod", "job").status.phase == PodPhase.SUCCEEDED
        finally:
            client.close()
            cri.close()
            srv.close()

    def test_daemon_builder(self, tmp_path):
        """crishim/serve.py's build_agent wires the same topology from
        flags (the daemon's entry path, minus the forever-loop)."""
        import argparse

        from kubegpu_tpu.crishim.serve import build_agent

        api = FakeApiServer()
        srv = ApiServerHTTP(api).start()
        args = argparse.Namespace(
            apiserver=srv.address, backend="mock", slice="v4-8",
            host_id=0, cri_socket=str(tmp_path / "cri.sock"),
            real_processes=False, env=None)
        client, cri, agent = build_agent(args)
        try:
            agent.register()
            assert api.get("Node", agent.node_name) is not None
            api.create("Pod", tpu_pod("p", chips=1, command=["x"]))
            api.bind_pod("p", agent.node_name)
            # inject the allocation annotation the scheduler would write
            from kubegpu_tpu.kubemeta.codec import (
                ALLOCATE_FROM_KEY,
                Allocation,
                AllocatedChip,
                allocation_to_annotation,
            )
            adv = agent.backend.discover()
            alloc = Allocation(
                node_name=agent.node_name,
                slice_id=adv.slice_id,
                chips=[AllocatedChip(
                    local_index=adv.chips[0].local_index,
                    coord=adv.chips[0].coord, millichips=1000)],
                worker_id=0, num_workers=1,
                coordinator_address="127.0.0.1:9999",
                worker_hostnames=["127.0.0.1"])
            api.patch_annotations(
                "Pod", "p",
                {ALLOCATE_FROM_KEY: allocation_to_annotation(alloc)})
            started = agent.run_once()
            assert len(started) == 1
            assert started[0].env["TPU_WORKER_ID"] == "0"
        finally:
            client.close()
            cri.close()
            srv.close()


class TestDaemonProcess:
    """The real thing: crishim.serve as a SEPARATE PROCESS.  Control
    plane in this process (HTTP façade), scheduler in this process,
    node daemon out-of-process — a pod goes submit → schedule → bind →
    (HTTP) → daemon → (CRI socket) → workload subprocess → reap →
    SUCCEEDED with no in-process shortcut anywhere."""

    @pytest.mark.slow
    @pytest.mark.parametrize("transport", ["json", "grpc"])
    def test_pod_runs_through_external_daemon(self, tmp_path, transport):
        import subprocess
        import sys as _sys

        from kubegpu_tpu.allocator import GangAllocator
        from kubegpu_tpu.scheduler import DeviceScheduler

        api = FakeApiServer()
        srv = ApiServerHTTP(api).start()
        proc = subprocess.Popen(
            [_sys.executable, "-m", "kubegpu_tpu.crishim.serve",
             "--apiserver", srv.address, "--backend", "mock",
             "--slice", "v4-8", "--transport", transport,
             "--cri-socket", str(tmp_path / "cri.sock"),
             "--real-processes", "--tick", "0.05",
             "--advertise-interval", "1"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            # wait for the daemon to register its node over HTTP
            deadline = time.monotonic() + 30
            node_name = None
            while time.monotonic() < deadline and node_name is None:
                assert proc.poll() is None, (
                    "daemon died at startup:\n" + proc.stderr.read())
                nodes = api.list("Node")
                if nodes:
                    node_name = nodes[0].name
                time.sleep(0.1)
            assert node_name, "daemon never registered a node"

            sched = DeviceScheduler(api, allocator=GangAllocator())
            api.create("Pod", tpu_pod(
                "hello", chips=1,
                command=[_sys.executable, "-c", "print('ran in daemon')"]))
            res = sched.run_once()
            assert res.scheduled == ["hello"]

            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if api.get("Pod", "hello").status.phase == \
                        PodPhase.SUCCEEDED:
                    break
                time.sleep(0.1)
            assert api.get("Pod", "hello").status.phase == \
                PodPhase.SUCCEEDED
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
            srv.close()

    @pytest.mark.slow
    def test_daemon_survives_apiserver_restart(self, tmp_path):
        """Control-plane restart with wiped state: the daemon must back
        off, re-register its Node, and keep serving — not die (the
        kubelet contract the retry loop implements)."""
        import socket
        import subprocess
        import sys as _sys

        # pre-pick a port so the restarted apiserver can reuse it
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()

        api1 = FakeApiServer()
        srv1 = ApiServerHTTP(api1, port=port).start()
        proc = subprocess.Popen(
            [_sys.executable, "-m", "kubegpu_tpu.crishim.serve",
             "--apiserver", f"http://127.0.0.1:{port}",
             "--backend", "mock", "--slice", "v4-8",
             "--cri-socket", str(tmp_path / "cri.sock"),
             "--tick", "0.05", "--advertise-interval", "0.2"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and not api1.list("Node"):
                assert proc.poll() is None, (
                    "daemon died at startup:\n" + proc.stderr.read())
                time.sleep(0.1)
            assert api1.list("Node"), "daemon never registered"

            srv1.close()   # apiserver dies; daemon starts erroring
            time.sleep(0.5)
            api2 = FakeApiServer()   # fresh state: Node object is GONE
            srv2 = ApiServerHTTP(api2, port=port).start()
            try:
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline and not api2.list("Node"):
                    assert proc.poll() is None, (
                        "daemon died during apiserver outage:\n"
                        + proc.stderr.read())
                    time.sleep(0.1)
                assert api2.list("Node"), \
                    "daemon never re-registered after restart"
            finally:
                srv2.close()
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

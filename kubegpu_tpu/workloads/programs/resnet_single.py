"""Single-chip ResNet training — BASELINE config 2 workload.

Asserts the injection granted exactly one chip, then trains a
structure-preserving ResNet on synthetic data.
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    from kubegpu_tpu.workloads.programs.distributed import read_env

    env = read_env()
    expect = os.environ.get("KUBETPU_EXPECT_CHIPS")
    if expect is not None and len(env.visible_chips) != int(expect):
        print(f"FAIL: expected {expect} chips, got {env.visible_chips}",
              file=sys.stderr)
        return 2

    import jax
    import jax.numpy as jnp
    import optax

    from kubegpu_tpu.models.resnet import (
        make_resnet_train_step, resnet_tiny, resnet50,
    )

    model = (resnet50(num_classes=100)
             if os.environ.get("RESNET_PRESET") == "50"
             else resnet_tiny())
    key = jax.random.PRNGKey(0)
    images = jax.random.normal(key, (8, 32, 32, 3))
    labels = jnp.arange(8) % 10
    variables = model.init(jax.random.PRNGKey(1), images, train=True)
    opt = optax.adam(1e-2)
    opt_state = opt.init(variables["params"])
    step = jax.jit(make_resnet_train_step(model, opt))
    params, bs = variables["params"], variables["batch_stats"]
    first = None
    for _ in range(int(os.environ.get("RESNET_STEPS", "6"))):
        params, bs, opt_state, loss = step(params, bs, opt_state,
                                           images, labels)
        first = first if first is not None else float(loss)
    print(f"resnet: first_loss={first:.4f} last_loss={float(loss):.4f} "
          f"chips={env.visible_chips}")
    if not float(loss) < first:
        print("FAIL: loss did not decrease", file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())

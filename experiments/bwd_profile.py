"""Backward-kernel decomposition at the bench shape (VERDICT r5 item #2).

Times, on the real chip, at the flagship bench attention shape
(B4 Hq16 Hkv4 T2048 D128 bf16):
  - flash fwd (no lse), fwd (+lse)
  - full bwd under dkv variants: grouped bq 256 (current), grouped
    bq 512 (expected scoped-vmem failure — documents the wall),
    de-grouped bq 512 (pays repeat_kv HBM), grouped bq 256 / bk 256
so the ~8 ms/step of suspected dq/dkv waste (BASELINE r4 bwd-block
sweep: bwd:fwd = 4.3x vs ~2.5x FLOPs-ideal) gets attributed to a
specific kernel + geometry before any re-design.  Uses the UNJITTED
``flash_attention_bwd.__wrapped__`` under fresh ``jax.jit`` per
variant: the module-level cap/budget constants are trace-time, so the
shared jit cache would otherwise mask the sweep.
"""

import importlib
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from kubegpu_tpu.benchmark import _fetch_rtt_s, _fetch_scalar  # noqa: E402

# the ops package re-exports the flash_attention FUNCTION; we need the
# submodule (its constants are the sweep's knobs)
fa = importlib.import_module("kubegpu_tpu.ops.flash_attention")

B, HQ, HKV, T, D = 4, 16, 4, 2048, 128
DT = jnp.bfloat16
RAW_BWD = fa.flash_attention_bwd.__wrapped__
# shipped defaults to restore between variants (cap is 512 since r5)
_ORIG_CAP = fa.DKV_GROUPED_BQ_CAP
_ORIG_BUDGET = fa.DKV_PANEL_BUDGET


def timeit(fn, state, iters=50):
    state = fn(state)
    _fetch_scalar(state)
    rtt = _fetch_rtt_s(state)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            state = fn(state)
        _fetch_scalar(state)
        best = min(best, max(time.perf_counter() - t0 - rtt, 1e-9))
    return best / iters


def main():
    key = jax.random.PRNGKey(0)
    kq, kk, kv, kg = jax.random.split(key, 4)
    q = jax.random.normal(kq, (B, HQ, T, D), DT)
    k = jax.random.normal(kk, (B, HKV, T, D), DT)
    v = jax.random.normal(kv, (B, HKV, T, D), DT)
    g = jax.random.normal(kg, (B, HQ, T, D), DT)

    fwd_s = timeit(lambda q_: fa.flash_attention(q_, k, v), q)
    print(f"fwd (no lse):      {fwd_s*1e3:8.3f} ms", flush=True)

    @jax.jit
    def fwd_lse(q_):
        o, _ = fa.flash_attention(q_, k, v, return_lse=True)
        return o

    out, lse = jax.jit(
        lambda: fa.flash_attention(q, k, v, return_lse=True))()
    fwdl_s = timeit(fwd_lse, q)
    print(f"fwd (+lse):        {fwdl_s*1e3:8.3f} ms", flush=True)

    variants = {
        "dq": ("dq only           bq512/bk512", 256, 6 << 20, 512, 512,
               "dq"),
        "dkv_cur": ("dkv only grouped  bq256/bk512", 256, 6 << 20, 512,
                    512, "dkv"),
        "full_cur": ("full grouped      bq256/bk512 (current)",
                     256, 6 << 20, 512, 512, "all"),
        "full_512": ("full grouped      bq512/bk512 (vmem?)",
                     512, 6 << 20, 512, 512, "all"),
        "dkv_bk256": ("dkv only grouped  bq256/bk256", 256, 6 << 20,
                      512, 256, "dkv"),
        "dkv_degroup": ("dkv only degroup  bq512/bk512", 512, 0, 512,
                        512, "dkv"),
        "dkv_degroup256": ("dkv only degroup  bq256/bk512", 256, 0,
                           512, 512, "dkv"),
    }
    want = sys.argv[1:] or list(variants)
    results = {}
    for label, cap, budget, bq, bk, part in (
            variants[w] for w in want):
        fa.DKV_GROUPED_BQ_CAP = cap
        fa.DKV_PANEL_BUDGET = budget
        try:
            full = jax.jit(lambda g_, bq=bq, bk=bk: RAW_BWD(
                q, k, v, out, lse, g_, True, bq, bk, False))
            _, dk_ref, _ = full(g)   # compile + numerics sample

            # keep the timed program's outputs LIVE (returning dq alone
            # lets XLA dead-code the whole dkv kernel — first attempt
            # measured exactly that) while chaining through a dq-shaped
            # value; the scalar graft costs one elementwise pass (~20us)
            if part == "dq":
                run = jax.jit(lambda g_, bq=bq, bk=bk: RAW_BWD(
                    q, k, v, out, lse, g_, True, bq, bk, False)[0])
            elif part == "dkv":
                def run(g_, bq=bq, bk=bk):
                    dq, dk, dv = RAW_BWD(q, k, v, out, lse, g_, True,
                                         bq, bk, False)
                    del dq   # DCE the dq kernel: isolate dkv
                    return (g_ + (dk[0, 0, 0, 0]
                                  + dv[0, 0, 0, 0]).astype(g_.dtype)
                            * jnp.bfloat16(1e-8))
                run = jax.jit(run)
            else:
                def run(g_, bq=bq, bk=bk):
                    dq, dk, dv = RAW_BWD(q, k, v, out, lse, g_, True,
                                         bq, bk, False)
                    return (dq + (dk[0, 0, 0, 0]
                                  + dv[0, 0, 0, 0]).astype(dq.dtype)
                            * jnp.bfloat16(1e-8))
                run = jax.jit(run)
            t_s = timeit(run, g)
            results[label] = (t_s, dk_ref)
            print(f"bwd {label}: {t_s*1e3:8.3f} ms "
                  f"(vs fwd {t_s/fwd_s:.2f}x)", flush=True)
        except Exception as e:
            print(f"bwd {label}: FAILED {type(e).__name__}: "
                  f"{str(e)[:160]}", flush=True)
        finally:
            fa.DKV_GROUPED_BQ_CAP = _ORIG_CAP
            fa.DKV_PANEL_BUDGET = _ORIG_BUDGET

    base = results.get("full grouped      bq256/bk512 (current)")
    if base:
        for label, (t_s, ref) in results.items():
            np.testing.assert_allclose(
                np.asarray(ref, np.float32),
                np.asarray(base[1], np.float32),
                atol=2e-2, rtol=2e-2, err_msg=label)
        print("cross-variant dk numerics OK", flush=True)


if __name__ == "__main__":
    main()

"""Observability — metrics registry + schedule trace.

The reference had glog verbosity only (SURVEY.md §6); KubeTPU ships the
counters/histograms the north-star metrics need (gang-schedule latency
histogram → p50, allocation locality gauge) and a structured per-decision
schedule trace (why each slice scored what).
"""

from kubegpu_tpu.obs.alerts import (
    Alert,
    AlertEngine,
    AlertRule,
    FlightRecorder,
)
from kubegpu_tpu.obs.chaos import (
    ChaosEvent,
    ChaosInjector,
    DispatchFailure,
    ReplicaDeadError,
    TickStallError,
)
from kubegpu_tpu.obs.cost import CostLedger
from kubegpu_tpu.obs.logging import configure as configure_logging
from kubegpu_tpu.obs.logging import get_logger
from kubegpu_tpu.obs.metrics import MetricsRegistry, global_registry
from kubegpu_tpu.obs.spans import (
    TRACE_ANNOTATION,
    TRACE_ENV,
    Span,
    SpanContext,
    Tracer,
)
from kubegpu_tpu.obs.trace import ScheduleTrace, TraceEvent
from kubegpu_tpu.obs.tsdb import SeriesStore

__all__ = ["MetricsRegistry", "global_registry", "ScheduleTrace",
           "TraceEvent", "get_logger", "configure_logging",
           "ChaosEvent", "ChaosInjector", "DispatchFailure",
           "ReplicaDeadError", "TickStallError",
           "Tracer", "Span", "SpanContext",
           "TRACE_ANNOTATION", "TRACE_ENV",
           "SeriesStore", "Alert", "AlertEngine", "AlertRule",
           "FlightRecorder", "CostLedger"]

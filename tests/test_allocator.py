"""Allocator tests — the reference's dominant test mode (SURVEY.md §5):
synthetic cluster state × synthetic requests ⇒ assert fit/no-fit, chosen
placement, scores.  Includes the property tests SURVEY.md §5 calls for:
random meshes × random gangs ⇒ valid contiguous assignment, never
double-booked."""

import random

import pytest

from kubegpu_tpu.allocator import (
    GangAllocator,
    GangRequest,
    SliceState,
    best_logical_order,
)
from kubegpu_tpu.topology import get_topology
from kubegpu_tpu.topology.slices import enumerate_placements
from kubegpu_tpu.tpuplugin import MockBackend
from kubegpu_tpu.tpuplugin.backend import MILLICHIPS_PER_CHIP


def make_slice(slice_type: str, slice_id: str | None = None,
               unhealthy: dict[int, set[int]] | None = None) -> SliceState:
    from kubegpu_tpu.topology.mesh import TOPOLOGY_REGISTRY
    spec = TOPOLOGY_REGISTRY[slice_type]
    advs = [
        MockBackend(slice_type, host_id=h, slice_id=slice_id,
                    unhealthy_chips=(unhealthy or {}).get(h, set())).discover()
        for h in range(spec.num_hosts)
    ]
    return SliceState.from_advertisements(advs)


class TestSingleChip:
    def test_one_chip_fits(self):
        st = make_slice("v4-8")
        asg = GangAllocator().find_assignment(
            [st], GangRequest("j", num_pods=1, chips_per_pod=1))
        assert asg is not None
        assert len(asg.pods) == 1
        assert len(asg.pods[0].chips) == 1
        assert asg.pods[0].chips[0].millichips == MILLICHIPS_PER_CHIP

    def test_no_fit_when_full(self):
        st = make_slice("v4-8")
        alloc = GangAllocator()
        slices = {st.slice_id: st}
        for i in range(4):
            a = alloc.find_assignment([st], GangRequest(f"j{i}", 1, 1))
            assert a is not None
            alloc.commit(slices, a)
        assert alloc.find_assignment([st], GangRequest("j5", 1, 1)) is None

    def test_pod_cannot_span_hosts(self):
        st = make_slice("v5e-16")  # 4 chips per host
        asg = GangAllocator().find_assignment(
            [st], GangRequest("j", num_pods=1, chips_per_pod=8))
        assert asg is None  # 8 > chips_per_host

    def test_unhealthy_chip_avoided(self):
        st = make_slice("v4-8", unhealthy={0: {0, 1, 2}})
        asg = GangAllocator().find_assignment([st], GangRequest("j", 1, 1))
        assert asg is not None
        assert asg.pods[0].chips[0].coord not in st.unhealthy
        # only one healthy chip → a 2-chip pod must fail
        assert GangAllocator().find_assignment(
            [st], GangRequest("j2", 1, 2)) is None


class TestGangs:
    def test_4pod_dp_gang_on_v4_8(self):
        """BASELINE config 3: 4-pod DP gang on one v4-8 host."""
        st = make_slice("v4-8")
        asg = GangAllocator().find_assignment(
            [st], GangRequest("dpjob", num_pods=4, chips_per_pod=1,
                              mesh_axes={"dp": 4}))
        assert asg is not None
        assert [p.pod_index for p in asg.pods] == [0, 1, 2, 3]
        coords = [p.chips[0].coord for p in asg.pods]
        assert len(set(coords)) == 4
        # 2x2 ring order keeps the DP ring fully ICI-local
        assert asg.locality == pytest.approx(1.0)

    def test_gang_atomicity(self):
        """5-chip ask on a 4-chip slice: nothing is allocated."""
        st = make_slice("v4-8")
        asg = GangAllocator().find_assignment(
            [st], GangRequest("big", num_pods=5, chips_per_pod=1))
        assert asg is None
        assert sum(st.used_millichips.values()) == 0

    def test_multihost_gang_v5e16(self):
        """BASELINE config 4 shape: 4 pods × 4 chips = whole v5e-16."""
        st = make_slice("v5e-16")
        asg = GangAllocator().find_assignment(
            [st], GangRequest("llama", num_pods=4, chips_per_pod=4,
                              mesh_axes={"dp": 4, "tp": 4}))
        assert asg is not None
        # each pod's 4 chips on one host
        for p in asg.pods:
            host_ids = {st.topo.chip_at(c.coord).host_id for c in p.chips}
            assert len(host_ids) == 1
        # distinct hosts for 4x4-chip pods
        assert len({p.host_id for p in asg.pods}) == 4
        # worker order: node names in worker order are deterministic
        assert [p.pod_index for p in asg.pods] == [0, 1, 2, 3]

    def test_gang_respects_occupancy(self):
        st = make_slice("v5e-16")
        alloc = GangAllocator()
        slices = {st.slice_id: st}
        a1 = alloc.find_assignment(
            [st], GangRequest("a", num_pods=2, chips_per_pod=4))
        alloc.commit(slices, a1)
        a2 = alloc.find_assignment(
            [st], GangRequest("b", num_pods=2, chips_per_pod=4))
        assert a2 is not None
        alloc.commit(slices, a2)
        used1 = {c.coord for p in a1.pods for c in p.chips}
        used2 = {c.coord for p in a2.pods for c in p.chips}
        assert not (used1 & used2)
        # slice now full
        assert alloc.find_assignment(
            [st], GangRequest("c", num_pods=1, chips_per_pod=1)) is None

    def test_rollback(self):
        st = make_slice("v4-8")
        alloc = GangAllocator()
        slices = {st.slice_id: st}
        a = alloc.find_assignment([st], GangRequest("a", 4, 1))
        alloc.commit(slices, a)
        alloc.rollback(slices, a)
        assert sum(st.used_millichips.values()) == 0

    def test_coordinator_and_hostnames(self):
        st = make_slice("v5e-16")
        alloc = GangAllocator()
        asg = alloc.find_assignment(
            [st], GangRequest("j", num_pods=4, chips_per_pod=4))
        addr, names = GangAllocator.coordinator_for(
            asg, {st.slice_id: st})
        assert addr.endswith(":8476")
        assert len(names) == 4
        assert names[0] == asg.pods[0].node_name


class TestLocalityScoring:
    def test_compact_preferred_on_v5e64(self):
        """A (4,4) logical mesh of 1-chip pods must land on a 4x4 physical
        block (grid mapping: 0.75 locality) — not a 1x16 line (0.375)."""
        st = make_slice("v5e-64")
        asg = GangAllocator().find_assignment(
            [st], GangRequest("j", num_pods=16, chips_per_pod=1,
                              mesh_axes={"dp": 4, "tp": 4}))
        assert asg is not None
        assert set(asg.placement.shape[:2]) == {4}
        assert asg.locality >= 0.7

    def test_tp_heavy_weighting_gets_local_tp(self):
        st = make_slice("v5e-64")
        asg = GangAllocator().find_assignment(
            [st], GangRequest(
                "llama", num_pods=16, chips_per_pod=4,
                mesh_axes={"dp": 4, "tp": 16},
                axis_weights={"tp": 10.0, "dp": 1.0}))
        assert asg is not None
        assert asg.locality > 0.9  # the ≥90% north-star bar

    def test_best_logical_order_closes_dp_ring(self):
        topo = get_topology("v5e-16")
        pl = enumerate_placements(topo, (4, 4, 1))[0]
        order, loc = best_logical_order(topo, pl, {"dp": 16})
        assert loc == pytest.approx(1.0)  # snake closes the cycle
        assert len(order) == 16


class TestNorthStarLocality:
    """BASELINE.md north-star: ≥90% ICI-link locality for sharded gangs.

    Locality is traffic-volume-weighted (tp ≫ dp — see
    DEFAULT_AXIS_WEIGHTS); each bench workload shape must clear 0.90 on an
    empty v5e-64."""

    @pytest.mark.parametrize("pods,chips,axes", [
        (4, 1, {"dp": 4}),
        (4, 4, {"dp": 4, "tp": 4}),
        (16, 4, {"dp": 4, "tp": 16}),
        (8, 4, {"dp": 2, "tp": 16}),
        (1, 4, {"dp": 1, "tp": 4}),
        (4, 4, {"dp": 4, "sp": 4}),       # ring-attention sequence axis
    ])
    def test_bench_shapes_meet_north_star(self, pods, chips, axes):
        st = make_slice("v5e-64")
        asg = GangAllocator().find_assignment(
            [st], GangRequest(gang_name="g", num_pods=pods,
                              chips_per_pod=chips, mesh_axes=axes))
        assert asg is not None
        assert asg.locality >= 0.90, (axes, asg.locality)

    def test_llama_v5e64_tp_dp_full_slice(self):
        """The headline config: Llama-3-8B pjit gang filling v5e-64."""
        st = make_slice("v5e-64")
        asg = GangAllocator().find_assignment(
            [st], GangRequest(gang_name="llama", num_pods=16,
                              chips_per_pod=4,
                              mesh_axes={"dp": 4, "tp": 16}))
        assert asg is not None
        assert asg.locality >= 0.90, asg.locality
        # every pod host-local, worker ids dense
        assert [p.pod_index for p in asg.pods] == list(range(16))


class TestFractional:
    def test_fractional_binpacks(self):
        """BASELINE config 5: two fractional jobs share one chip."""
        st = make_slice("v4-8")
        alloc = GangAllocator()
        slices = {st.slice_id: st}
        a1 = alloc.find_assignment(
            [st], GangRequest("f1", millitpu_per_pod=400))
        alloc.commit(slices, a1)
        a2 = alloc.find_assignment(
            [st], GangRequest("f2", millitpu_per_pod=500))
        alloc.commit(slices, a2)
        assert a1.pods[0].chips[0].coord == a2.pods[0].chips[0].coord
        # 3 whole chips still free for slices
        asg = alloc.find_assignment(
            [st], GangRequest("whole", num_pods=3, chips_per_pod=1))
        assert asg is not None

    def test_fractional_no_overcommit(self):
        st = make_slice("v4-8")
        alloc = GangAllocator()
        slices = {st.slice_id: st}
        for i in range(4 * 2):  # 8 x 500 fills all 4 chips
            a = alloc.find_assignment(
                [st], GangRequest(f"f{i}", millitpu_per_pod=500))
            assert a is not None
            alloc.commit(slices, a)
        assert alloc.find_assignment(
            [st], GangRequest("f9", millitpu_per_pod=500)) is None

    def test_fractional_request_validation(self):
        with pytest.raises(ValueError):
            GangRequest("x", num_pods=2, millitpu_per_pod=500)
        with pytest.raises(ValueError):
            GangRequest("x", chips_per_pod=1, millitpu_per_pod=500)
        with pytest.raises(ValueError):
            GangRequest("x", millitpu_per_pod=1500)


class TestMultiSlice:
    def test_best_fit_across_slices(self):
        """Prefer filling the fuller slice (bin packing)."""
        s1 = make_slice("v5e-16", slice_id="s1")
        s2 = make_slice("v5e-16", slice_id="s2")
        alloc = GangAllocator()
        slices = {"s1": s1, "s2": s2}
        a = alloc.find_assignment(
            [s1, s2], GangRequest("warm", num_pods=2, chips_per_pod=4))
        alloc.commit(slices, a)
        warm = a.slice_id
        b = alloc.find_assignment(
            [s1, s2], GangRequest("next", num_pods=1, chips_per_pod=4))
        assert b.slice_id == warm  # fill-weight steers to the used slice

    def test_spillover_when_full(self):
        s1 = make_slice("v4-8", slice_id="s1")
        s2 = make_slice("v4-8", slice_id="s2")
        alloc = GangAllocator()
        slices = {"s1": s1, "s2": s2}
        a = alloc.find_assignment([s1, s2], GangRequest("a", 4, 1))
        alloc.commit(slices, a)
        b = alloc.find_assignment([s1, s2], GangRequest("b", 4, 1))
        assert b is not None
        assert b.slice_id != a.slice_id


class TestProperties:
    """SURVEY.md §5 (a): random meshes × random gangs ⇒ always valid."""

    @pytest.mark.parametrize("seed", range(10))
    def test_random_workload_never_double_books(self, seed):
        rng = random.Random(seed)
        slice_type = rng.choice(["v4-8", "v5e-8", "v5e-16", "v5e-64"])
        st = make_slice(slice_type)
        alloc = GangAllocator(max_placements_per_shape=16)
        slices = {st.slice_id: st}
        live: list = []
        for step in range(30):
            if live and rng.random() < 0.4:
                asg = live.pop(rng.randrange(len(live)))
                alloc.rollback(slices, asg)
                continue
            cph = st.spec.chips_per_host
            c = rng.choice([1, 2, cph])
            max_pods = st.spec.num_chips // c
            p = rng.randint(1, max(1, max_pods))
            asg = alloc.find_assignment(
                [st], GangRequest(f"g{step}", num_pods=p, chips_per_pod=c))
            if asg is None:
                continue
            # validity: right pod count, chunk sizes, host-locality
            assert len(asg.pods) == p
            for pa in asg.pods:
                assert len(pa.chips) == c
                hosts = {st.topo.chip_at(ch.coord).host_id
                         for ch in pa.chips}
                assert len(hosts) == 1
            alloc.commit(slices, asg)  # raises on double-book
            live.append(asg)
        # conservation: releasing everything zeroes occupancy
        for asg in live:
            alloc.rollback(slices, asg)
        assert all(v == 0 for v in st.used_millichips.values())

"""Flash attention: pallas TPU kernel + XLA reference.

Design per /opt/skills/guides/pallas_guide.md: grid over (batch·heads,
q-blocks); K/V live in VMEM per (b,h); online-softmax accumulation over
k-blocks with a fori_loop; f32 accumulators (`preferred_element_type`);
causal masking via broadcasted iotas.  Falls back to a fused-by-XLA
einsum+softmax implementation off-TPU (and for odd shapes), so every
caller works identically on CPU tests and TPU benches.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def repeat_kv(q: jax.Array, k: jax.Array,
              v: jax.Array) -> tuple[jax.Array, jax.Array]:
    """GQA: repeat kv heads up to the query head count (Hq % Hkv == 0)."""
    hq, hkv = q.shape[1], k.shape[1]
    if hq == hkv:
        return k, v
    if hq % hkv:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    rep = hq // hkv
    return jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1)


def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                  causal: bool = True,
                  scale: float | None = None) -> jax.Array:
    """Reference attention.  q: [B, Hq, T, D]; k/v: [B, Hkv, S, D].
    GQA via ``repeat_kv``.  Causal masking is *end-aligned* when t < s
    (query i attends keys <= i + s - t, the decode/suffix convention)."""
    b, hq, t, d = q.shape
    _, hkv, s, _ = k.shape
    if causal and t > s:
        raise ValueError(
            f"causal attention with more queries ({t}) than keys ({s}) is "
            "ill-defined (queries before the key horizon attend nothing)")
    k, v = repeat_kv(q, k, v)
    scale = scale if scale is not None else d ** -0.5
    scores = jnp.einsum("bhtd,bhsd->bhts", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, s), dtype=bool), k=s - t)
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhts,bhsd->bhtd", probs.astype(v.dtype), v)


# Default block sizes, tuned on v5e (bench sweep 2026-07-30: 256/512 is
# ~3.4x faster than 128/128 on B4·H16·T2048·D64 and beats the XLA
# reference ~3.3x; 128-multiples keep the MXU tiled on every generation).
BLOCK_Q = 256
BLOCK_K = 512
# Backward prefers a taller q-block (bench sweep 2026-07-30 on v5e, both
# hd=64 and hd=128: 512/512 beats the forward's 256/512 by ~1.5-2x — the
# dq and dkv kernels run 3 matmuls per (q,k) block pair, so amortizing
# the per-block softmax recompute over more rows wins).
BLOCK_Q_BWD = 512
BLOCK_K_BWD = 512
# lse/delta ride in [*, t, LSE_LANES] tiles: queries on sublanes (so
# per-row broadcasts need no transpose), a full size-8 lane dim to
# satisfy the TPU (8, 128)-or-full block rule at f32 tiling.
LSE_LANES = 8
# Resident q/do/lse/delta panel budget for the grouped dkv backward
# kernel (see flash_attention_bwd): beyond this the geometry de-groups
# via repeat_kv instead of risking a scoped-vmem compile error.
DKV_PANEL_BUDGET = 6 * 1024 * 1024
# Grouped-dkv q-block cap.  512 needs BWD_VMEM_LIMIT's headroom (the
# resident panels + 512-tall score scratch overflow Mosaic's default
# 16 MiB scoped limit — the r1-r4 reason this sat at 256); the r5
# interleaved same-window A/B measured bq512 ~9% faster than bq256
# (3.98 vs 4.36 ms medians) with bq128/bk256 strictly worse.
DKV_GROUPED_BQ_CAP = 512
# Scoped-VMEM ceiling for the backward kernels: Mosaic's 16 MiB default
# is conservative (v5e cores carry far more VMEM); the grouped dkv
# kernel keeps whole [group·t, d] panels resident and needs the
# headroom for the taller q-blocks the bench sweep favors.
BWD_VMEM_LIMIT = 64 * 1024 * 1024
# exp2-folded softmax (VERDICT r5 item #4: test the transcendental
# hypothesis).  The TPU VPU's native transcendental is exp2; exp(x)
# lowers to exp2(x·log2e) with a separate multiply per element.  With
# the fold ON, scores are computed directly in the base-2 domain — the
# log2(e) factor folds into the existing 1/sqrt(d) score scale (one
# scalar at trace time, zero extra per-element work) and the
# softmax/online-rescale transcendentals become exp2.  Mathematically
# identical (exp(x) == exp2(x·log2e)); numerically within 1 ulp of the
# exp formulation.  The emitted lse stays in NATURAL log (the
# custom-vjp residual contract; the backward kernels re-fold it by
# log2e at trace-in).  Module-level knob so experiments/exp2_ab.py and
# step_ab.py can A/B it in one window (setattr + jax.clear_caches()).
SOFTMAX_EXP2 = True
LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453


_warned_fallback: set = set()


def _blocks_ok(t: int, s: int, block_q: int, block_k: int,
               interpret: bool = False) -> bool:
    """Whether (clamped) blocks can take the pallas path: they must
    tile (t, s) exactly AND — on the compiled path — be sublane-aligned
    (%8): the kernels slice k/v panels and the dkv q-panel on the
    second-minor dim, so an off-8 block (e.g. t=33 → block 33, which
    *does* divide) would hand Mosaic a misaligned window (ADVICE r4
    medium).  Interpret mode has no tiling hardware to violate."""
    if t % block_q or s % block_k:
        return False
    if interpret:
        return True
    return block_q % 8 == 0 and block_k % 8 == 0


def _warn_fallback_once(t: int, s: int, block_q: int, block_k: int) -> None:
    """A LOUD (once per shape) note when block alignment silently
    routes to the XLA path: the r4 profiler trace caught the flagship
    train step running O(T²) XLA attention for two whole rounds
    because its loss sliced T to 2047 — a silent fallback on the hot
    path must never be silent again.  Under KUBETPU_REQUIRE_PALLAS
    the fallback RAISES instead (VERDICT r4 next-item #3)."""
    from kubegpu_tpu.ops.strict import fallback
    fallback("flash_attention",
             f"shape (t={t}, s={s}) does not tile aligned blocks "
             f"({block_q}, {block_k}); XLA O(T²) attention would run")
    key = (t, s, block_q, block_k)
    if key in _warned_fallback:
        return
    _warned_fallback.add(key)
    import sys
    print(f"flash_attention: shape (t={t}, s={s}) not divisible by "
          f"blocks ({block_q}, {block_k}) — falling back to XLA "
          "attention (O(T²) scores materialized)", file=sys.stderr)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret", "return_lse"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, block_q: int = BLOCK_Q,
                    block_k: int = BLOCK_K,
                    interpret: bool = False,
                    return_lse: bool = False):
    """Pallas flash attention with *grouped* GQA reads.

    Shapes as ``xla_attention``.  K/V are NOT repeated up to the query
    head count: the grid is (b·hkv, group, q-blocks) and the K/V block
    index maps are constant across the ``group`` dimension, so the
    pallas pipeline fetches each (b, kv-head) K/V panel from HBM once
    and reuses it for all ``group`` query heads — K/V HBM traffic drops
    by the GQA group factor vs the repeat_kv formulation, and the 4×
    repeated K/V copies are never materialized at all.

    With ``return_lse`` also returns the per-row logsumexp ``L`` of
    shape [B, Hq, T] (f32) — the residual the backward kernels need.
    """
    from jax.experimental import pallas as pl

    b, hq, t, d = q.shape
    _, hkv, s, _ = k.shape
    if causal and t > s:
        raise ValueError(
            f"causal attention with more queries ({t}) than keys ({s}) is "
            "ill-defined (queries before the key horizon attend nothing)")
    if hq % hkv:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    group = hq // hkv
    scale = d ** -0.5
    causal_offset = s - t  # end-aligned, matching xla_attention
    block_q = min(block_q, t)
    block_k = min(block_k, s)
    if not _blocks_ok(t, s, block_q, block_k, interpret):
        _warn_fallback_once(t, s, block_q, block_k)
        out = xla_attention(q, k, v, causal=causal)
        if not return_lse:
            return out
        return out, _xla_lse(q, k, causal, scale)

    # q head h = kv head (h // group), query-group (h % group) — the
    # same consecutive-repeat convention as ``repeat_kv``.
    qf = q.reshape(b * hq, t, d)
    kf = k.reshape(b * hkv, s, d)
    vf = v.reshape(b * hkv, s, d)
    num_k_blocks = s // block_k

    # exp2 fold (SOFTMAX_EXP2, trace-time): scores carry the log2e
    # factor inside the score scale, so softmax transcendentals are
    # native exp2 — same values, one fewer per-element multiply chain
    # on the VPU than the exp lowering.
    exp2_fold = bool(SOFTMAX_EXP2)
    sscale = scale * LOG2E if exp2_fold else scale
    _exp = jnp.exp2 if exp2_fold else jnp.exp

    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref=None):
        qi = pl.program_id(2)
        # Dots run in the INPUT dtype with f32 accumulation
        # (preferred_element_type): bf16 inputs feed the MXU natively —
        # the former .astype(f32) upcasts forced multi-pass f32 matmuls
        # (r5 on-chip attribution: the bwd dkv kernel sat at 2.8x fwd
        # where ~1.5x is FLOPs-ideal) and doubled the VMEM block
        # footprint.  Scale applies to the f32 scores, not to q.
        qb = q_ref[0]                              # [bq, d]

        def body(ki, carry):
            o_acc, m_acc, l_acc = carry
            kb = k_ref[0, pl.ds(ki * block_k, block_k), :]
            vb = v_ref[0, pl.ds(ki * block_k, block_k), :]
            sc = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sscale  # [bq, bk]
            if causal:
                qpos = causal_offset + qi * block_q + \
                    jax.lax.broadcasted_iota(
                        jnp.int32, (block_q, block_k), 0)
                kpos = ki * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                sc = jnp.where(qpos >= kpos, sc, NEG_INF)
            m_new = jnp.maximum(m_acc, sc.max(axis=-1, keepdims=True))
            p = _exp(sc - m_new)
            alpha = _exp(m_acc - m_new)
            l_new = alpha * l_acc + p.sum(axis=-1, keepdims=True)
            o_new = alpha * o_acc + jax.lax.dot_general(
                p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return o_new, m_new, l_new

        o0 = jnp.zeros((block_q, d), jnp.float32)
        m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
        l0 = jnp.zeros((block_q, 1), jnp.float32)
        if causal:
            # k-blocks strictly past this q-block's LAST row's horizon
            # contribute nothing; the last visible key index is
            # offset + (qi+1)*block_q - 1.
            horizon = causal_offset + (qi + 1) * block_q - 1
            n_iter = jnp.minimum(num_k_blocks, horizon // block_k + 1)
        else:
            n_iter = num_k_blocks
        o_acc, m_acc, l_acc = jax.lax.fori_loop(0, n_iter, body,
                                                (o0, m0, l0))
        l_safe = jnp.maximum(l_acc, 1e-30)
        o_ref[0] = (o_acc / l_safe).astype(o_ref.dtype)
        if lse_ref is not None:
            # lane-padded [block_q, LSE_LANES] tile (TPU blocks need the
            # last two dims (8k, 128m) or full; queries stay on sublanes
            # so neither this write nor the backward's read transposes).
            # Under the exp2 fold m_acc is in base-2 units; one scalar
            # multiply per row converts the emitted lse back to the
            # natural-log residual contract.
            m_nat = m_acc * LN2 if exp2_fold else m_acc
            lse_ref[0] = jnp.broadcast_to(m_nat + jnp.log(l_safe),
                                          (block_q, LSE_LANES))

    # K/V index maps ignore (g, j): consecutive grid steps within one
    # (b, kv-head) see the same block index, so pallas keeps the panel
    # resident in VMEM instead of re-fetching it per query head.
    grid = (b * hkv, group, t // block_q)
    q_spec = pl.BlockSpec((1, block_q, d),
                          lambda i, g, j: (i * group + g, j, 0))
    out_shape = [jax.ShapeDtypeStruct(qf.shape, q.dtype)]
    out_specs = [q_spec]
    if return_lse:   # inference forwards skip the extra f32 HBM output
        out_shape.append(
            jax.ShapeDtypeStruct((b * hq, t, LSE_LANES), jnp.float32))
        out_specs.append(
            pl.BlockSpec((1, block_q, LSE_LANES),
                         lambda i, g, j: (i * group + g, j, 0)))
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            q_spec,
            pl.BlockSpec((1, s, d), lambda i, g, j: (i, 0, 0)),
            pl.BlockSpec((1, s, d), lambda i, g, j: (i, 0, 0)),
        ],
        out_specs=out_specs,
        interpret=interpret,
    )(qf, kf, vf)
    if not return_lse:
        return res[0].reshape(b, hq, t, d)
    out, lse = res
    return (out.reshape(b, hq, t, d),
            lse[:, :, 0].reshape(b, hq, t))


def _xla_lse(q, k, causal, scale):
    """Per-row logsumexp of the (masked) score matrix — the fallback's
    version of the kernel's L output."""
    b, hq, t, d = q.shape
    s = k.shape[2]
    if hq != k.shape[1]:
        k = jnp.repeat(k, hq // k.shape[1], axis=1)
    scores = jnp.einsum("bhtd,bhsd->bhts", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, s), dtype=bool), k=s - t)
        scores = jnp.where(mask, scores, NEG_INF)
    return jax.nn.logsumexp(scores, axis=-1)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def flash_attention_bwd(q, k, v, out, lse, do, causal: bool = True,
                        block_q: int = BLOCK_Q_BWD,
                        block_k: int = BLOCK_K_BWD,
                        interpret: bool = False):
    """Pallas flash-attention backward: (dq, dk, dv) with the logsumexp
    trick — no T² residual was saved; scores recompute blockwise.

    Two kernels (the standard TPU split, avoiding cross-grid-step
    accumulation races): dq iterates k-blocks per q-block; dk/dv
    iterates q-blocks per k-block.  GQA runs *grouped* like the
    forward: K/V stay at Hkv heads, the dq grid carries a group
    dimension with group-constant K/V index maps, and the dkv kernel
    statically unrolls the group so dk/dv are summed over the query
    group in-kernel (returning [B, Hkv, S, D] directly — no repeated
    dk/dv materialization + XLA reduction afterwards).  Requires
    block-tiling shapes (callers fall back to the XLA VJP otherwise).
    """
    from jax.experimental import pallas as pl

    b, h, t, d = q.shape
    hkv = k.shape[1]
    s = k.shape[2]
    if h % hkv:
        raise ValueError(f"query heads {h} not a multiple of kv heads {hkv}")
    group = h // hkv
    scale = d ** -0.5
    causal_offset = s - t
    block_q = min(block_q, t)
    block_k = min(block_k, s)
    assert _blocks_ok(t, s, block_q, block_k, interpret), \
        f"bwd blocks ({block_q},{block_k}) must tile+align (t={t}, s={s})"
    num_k_blocks = s // block_k
    num_q_blocks = t // block_q
    # Geometries whose resident [group·t, d] panels can't fit the dkv
    # kernel's VMEM (e.g. group 8 · t 4096) de-group THAT kernel only:
    # K/V repeat up to the query head count for the dkv call (paying
    # its extra HBM traffic — better than a scoped-vmem compile
    # error) and dk/dv are summed over the group afterwards.  The dq
    # kernel's layout is per-query-head regardless, so it stays
    # grouped either way.
    panel_bytes = group * t * (q.dtype.itemsize * 2 * d
                               + 2 * LSE_LANES * 4)
    degroup_kv = group > 1 and panel_bytes > DKV_PANEL_BUDGET
    group_kv = 1 if degroup_kv else group
    # The grouped dkv kernel keeps the whole [group·t, d] q/do panels
    # resident in VMEM; under Mosaic's default 16 MiB scoped limit that
    # capped the q-block at 256 (r1-r4).  BWD_VMEM_LIMIT raises the
    # ceiling, and the r5 interleaved A/B put the cap at 512 (see
    # DKV_GROUPED_BQ_CAP) — gcd against t so an arbitrary caller block
    # (e.g. 384) can never truncate rows out of the dk/dv accumulation.
    block_q_kv = (math.gcd(t, min(block_q, DKV_GROUPED_BQ_CAP))
                  if group_kv > 1 else block_q)
    num_q_blocks_kv = t // block_q_kv

    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * hkv, s, d)
    vf = v.reshape(b * hkv, s, d)
    dof = do.reshape(b * h, t, d)
    lsef = jnp.broadcast_to(
        lse.reshape(b * h, t, 1), (b * h, t, LSE_LANES))
    # D_i = rowsum(dO ∘ O): cheap elementwise+reduce, fused by XLA
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1).reshape(b * h, t, 1), (b * h, t, LSE_LANES))

    # exp2 fold (see the forward): scores carry log2e inside the score
    # scale and p recovers via native exp2 against a pre-folded lse.
    # ds keeps the NATURAL scale — d(sc_nat)/d(q·k) is scale, not
    # scale·log2e; the fold only re-bases the softmax recompute.
    exp2_fold = bool(SOFTMAX_EXP2)
    sscale = scale * LOG2E if exp2_fold else scale
    _exp = jnp.exp2 if exp2_fold else jnp.exp
    lse_fold = LOG2E if exp2_fold else 1.0

    def dq_kernel(q_ref, k_ref, v_ref, lse_ref, delta_ref, do_ref,
                  dq_ref):
        qi = pl.program_id(2)
        # input-dtype dots, f32 accumulation — see the forward kernel's
        # note (bf16 feeds the MXU natively; scale folds into f32
        # scores / ds instead of upcasting q)
        qb = q_ref[0]                                # [bq, d]
        dob = do_ref[0]                              # [bq, d]
        lse_b = lse_ref[0][:, 0:1] * lse_fold        # [bq, 1]
        delta_b = delta_ref[0][:, 0:1]               # [bq, 1]

        def body(ki, dq_acc):
            kb = k_ref[0, pl.ds(ki * block_k, block_k), :]
            vb = v_ref[0, pl.ds(ki * block_k, block_k), :]
            sc = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sscale  # [bq, bk]
            if causal:
                qpos = causal_offset + qi * block_q + \
                    jax.lax.broadcasted_iota(
                        jnp.int32, (block_q, block_k), 0)
                kpos = ki * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                sc = jnp.where(qpos >= kpos, sc, NEG_INF)
            p = _exp(sc - lse_b)                     # [bq, bk]
            dp = jax.lax.dot_general(
                dob, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [bq, bk]
            ds = (p * (dp - delta_b) * scale).astype(kb.dtype)
            return dq_acc + jax.lax.dot_general(
                ds, kb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        if causal:
            horizon = causal_offset + (qi + 1) * block_q - 1
            n_iter = jnp.minimum(num_k_blocks, horizon // block_k + 1)
        else:
            n_iter = num_k_blocks
        dq = jax.lax.fori_loop(
            0, n_iter, body, jnp.zeros((block_q, d), jnp.float32))
        dq_ref[0] = dq.astype(dq_ref.dtype)

    def dkv_kernel(q_ref, k_ref, v_ref, lse_ref, delta_ref, do_ref,
                   dk_ref, dv_ref):
        # q/do/lse/delta arrive as the full [group·t, ...] panel for
        # this (b, kv-head); row g·t + i is query head g's row i.
        # Input-dtype dots, f32 accumulation (see forward) — the f32
        # panel copies this kernel used to make were both the VMEM
        # ceiling that capped block_q_kv at 256 and a multi-pass f32
        # MXU tax.
        ki = pl.program_id(1)
        kb = k_ref[0]                                # [bk, d]
        vb = v_ref[0]                                # [bk, d]

        def make_body(goff):
            def body(qi, carry):
                dk_acc, dv_acc = carry
                rows = pl.ds(goff + qi * block_q_kv, block_q_kv)
                qb = q_ref[0, rows, :]
                dob = do_ref[0, rows, :]
                lse_b = lse_ref[0, rows, 0:1] * lse_fold
                delta_b = delta_ref[0, rows, 0:1]
                sc = jax.lax.dot_general(
                    qb, kb, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * sscale
                if causal:
                    qpos = causal_offset + qi * block_q_kv + \
                        jax.lax.broadcasted_iota(
                            jnp.int32, (block_q_kv, block_k), 0)
                    kpos = ki * block_k + jax.lax.broadcasted_iota(
                        jnp.int32, (block_q_kv, block_k), 1)
                    sc = jnp.where(qpos >= kpos, sc, NEG_INF)
                p = _exp(sc - lse_b)                     # [bq, bk]
                dv_new = dv_acc + jax.lax.dot_general(
                    p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)  # [bk, d]
                dp = jax.lax.dot_general(
                    dob, vb, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)  # [bq, bk]
                ds = (p * (dp - delta_b) * scale).astype(qb.dtype)
                dk_new = dk_acc + jax.lax.dot_general(
                    ds, qb, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)  # [bk, d]
                return dk_new, dv_new
            return body

        if causal:
            # q-blocks whose whole range sits before this k-block's
            # first visible query contribute nothing; -1 keeps the
            # bound conservative (masking zeroes any extra block)
            lo = jnp.maximum(
                0, (ki * block_k - causal_offset) // block_q_kv - 1)
        else:
            lo = 0
        dk = jnp.zeros((block_k, d), jnp.float32)
        dv = jnp.zeros((block_k, d), jnp.float32)
        for g in range(group_kv):   # static unroll: sum the query group
            dk, dv = jax.lax.fori_loop(lo, num_q_blocks_kv,
                                       make_body(g * t), (dk, dv))
        dk_ref[0] = dk.astype(dk_ref.dtype)
        dv_ref[0] = dv.astype(dv_ref.dtype)

    if interpret:
        cparams = {}
    else:
        from jax.experimental.pallas import tpu as pltpu
        # spelled CompilerParams on the driver's jax, TPUCompilerParams
        # on older images — same jax-generation split compat_shard_map
        # papers over
        cp_cls = getattr(pltpu, "CompilerParams", None) \
            or pltpu.TPUCompilerParams
        cparams = {"compiler_params": cp_cls(
            vmem_limit_bytes=BWD_VMEM_LIMIT)}
    qh_spec = pl.BlockSpec((1, block_q, d),
                           lambda i, g, j: (i * group + g, j, 0))
    lseh_spec = pl.BlockSpec((1, block_q, LSE_LANES),
                             lambda i, g, j: (i * group + g, j, 0))
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=(b * hkv, group, num_q_blocks),
        in_specs=[
            qh_spec,
            pl.BlockSpec((1, s, d), lambda i, g, j: (i, 0, 0)),
            pl.BlockSpec((1, s, d), lambda i, g, j: (i, 0, 0)),
            lseh_spec,
            lseh_spec,
            qh_spec,
        ],
        out_specs=qh_spec,
        interpret=interpret,
        **cparams,
    )(qf, kf, vf, lsef, delta, dof)
    # dkv reads the whole query group per (b, kv-head): view the
    # [b·h, t, ...] panels as [b·hkv, group·t, ...] (free reshape).
    # De-grouped, every view keeps one query head per row block and
    # K/V repeat up to b·h heads.
    heads_kv = b * h if degroup_kv else b * hkv
    if degroup_kv:
        kkv = jnp.repeat(k, group, axis=1).reshape(b * h, s, d)
        vkv = jnp.repeat(v, group, axis=1).reshape(b * h, s, d)
    else:
        kkv, vkv = kf, vf
    qg = qf.reshape(heads_kv, group_kv * t, d)
    dog = dof.reshape(heads_kv, group_kv * t, d)
    lseg = lsef.reshape(heads_kv, group_kv * t, LSE_LANES)
    deltag = delta.reshape(heads_kv, group_kv * t, LSE_LANES)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        out_shape=[
            jax.ShapeDtypeStruct(kkv.shape, k.dtype),
            jax.ShapeDtypeStruct(vkv.shape, v.dtype),
        ],
        grid=(heads_kv, num_k_blocks),
        in_specs=[
            pl.BlockSpec((1, group_kv * t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, group_kv * t, LSE_LANES),
                         lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, group_kv * t, LSE_LANES),
                         lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, group_kv * t, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        ],
        interpret=interpret,
        **cparams,
    )(qg, kkv, vkv, lseg, deltag, dog)
    if degroup_kv:   # sum the per-query-head dk/dv over each group
        dk = dk.reshape(b, hkv, group, s, d).sum(
            axis=2, dtype=jnp.float32).astype(k.dtype)
        dv = dv.reshape(b, hkv, group, s, d).sum(
            axis=2, dtype=jnp.float32).astype(v.dtype)
        return dq.reshape(b, h, t, d), dk, dv
    return (dq.reshape(b, h, t, d), dk.reshape(b, hkv, s, d),
            dv.reshape(b, hkv, s, d))


# ---------------------------------------------------------------------------
# Differentiable wrapper: pallas forward AND pallas backward.
#
# pallas_call has no automatic autodiff path, so training traces need a
# custom VJP.  Forward saves only (q, k, v, out, logsumexp) — no T²
# residuals (flash attention's memory trade); backward recomputes scores
# blockwise in the two kernels of :func:`flash_attention_bwd`.  Shapes
# that don't tile the blocks fall back to differentiating the XLA
# reference instead.  GQA stays *grouped* through this boundary: K/V
# (and dk/dv) keep Hkv heads end-to-end — the dkv kernel sums over the
# query group in-kernel.
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_diff(q, k, v, causal, interpret):
    return flash_attention(q, k, v, causal=causal, interpret=interpret)


def _bwd_blocks(t: int, s: int) -> tuple[int, int]:
    """Backward block sizes for a given (t, s): the taller bwd defaults
    when they tile, else the forward's blocks (which the pallas-path
    gate already guarantees tile) — e.g. t=768 tiles 256 but not 512,
    and must not lose the pallas backward over it."""
    bq = BLOCK_Q_BWD if t % min(BLOCK_Q_BWD, t) == 0 else BLOCK_Q
    bk = BLOCK_K_BWD if s % min(BLOCK_K_BWD, s) == 0 else BLOCK_K
    return bq, bk


def _flash_diff_fwd(q, k, v, causal, interpret):
    t, s = q.shape[2], k.shape[2]
    if not _blocks_ok(t, s, min(BLOCK_Q, t), min(BLOCK_K, s), interpret):
        # fallback shapes: no lse; bwd re-derives through XLA
        return (flash_attention(q, k, v, causal=causal,
                                interpret=interpret),
                (q, k, v, None, None))
    out, lse = flash_attention(q, k, v, causal=causal,
                               interpret=interpret, return_lse=True)
    return out, (q, k, v, out, lse)


def _flash_diff_bwd(causal, interpret, res, g):
    q, k, v, out, lse = res
    if lse is None:
        _, vjp = jax.vjp(
            lambda q_, k_, v_: xla_attention(q_, k_, v_, causal=causal),
            q, k, v)
        return vjp(g)
    bq, bk = _bwd_blocks(q.shape[2], k.shape[2])
    return flash_attention_bwd(q, k, v, out, lse, g, causal=causal,
                               block_q=bq, block_k=bk,
                               interpret=interpret)


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              causal: bool = True, impl: str = "auto") -> jax.Array:
    """Dispatch: pallas on TPU, XLA elsewhere.  ``impl`` ∈ auto | pallas |
    pallas_interpret | xla."""
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl in ("pallas", "pallas_interpret"):
        # GQA stays grouped through the kernels — no repeat_kv
        return _flash_diff(q, k, v, causal, impl == "pallas_interpret")
    return xla_attention(q, k, v, causal=causal)

"""Golden-bytes tests for the hand-rolled runtime.v1 proto codec.

VERDICT r4 next-item #5's done bar: the encodings are checked against
HAND-COMPUTED byte strings (not just round-trips), so the codec can't
be self-consistently wrong about the wire format a stock kubelet
speaks.  Wire rules under test: varint field keys (num << 3 | wt),
LEB128 varints, two's-complement negative ints, length-delimited
strings/messages, map entries as {key=1, value=2} submessages,
repeated fields as repeated tags, proto3 default elision, and
unknown-field skipping."""

import pytest

from kubegpu_tpu.crishim.protowire import (
    MESSAGES,
    decode_message,
    decode_varint,
    encode_message,
    encode_varint,
)


class TestVarint:
    @pytest.mark.parametrize("n,raw", [
        (0, b"\x00"),
        (1, b"\x01"),
        (127, b"\x7f"),
        (128, b"\x80\x01"),
        (300, b"\xac\x02"),               # the protobuf docs' example
        (1 << 32, b"\x80\x80\x80\x80\x10"),
    ])
    def test_known_encodings(self, n, raw):
        assert encode_varint(n) == raw
        assert decode_varint(raw, 0) == (n, len(raw))

    def test_negative_int_is_twos_complement_10_bytes(self):
        # -1 as int64: 0xFFFFFFFFFFFFFFFF -> ten 0xff..0x01 bytes
        raw = encode_varint(-1)
        assert raw == b"\xff" * 9 + b"\x01"
        v, _ = decode_varint(raw, 0)
        assert v == (1 << 64) - 1

    def test_truncated_varint_raises(self):
        with pytest.raises(ValueError, match="truncated"):
            decode_varint(b"\x80", 0)


class TestGoldenMessages:
    def test_version_request(self):
        schema = MESSAGES["Version"][0]
        # field 1 (string "v1"): key = 1<<3|2 = 0x0a, len 2
        assert encode_message(schema, {"version": "v1"}) == \
            b"\x0a\x02v1"
        assert encode_message(schema, {}) == b""   # defaults elided

    def test_pull_image_request(self):
        schema = MESSAGES["PullImage"][0]
        # image (field 1, msg) { image (field 1, string) = "a:b" }
        inner = b"\x0a\x03a:b"
        want = b"\x0a" + bytes([len(inner)]) + inner
        assert encode_message(schema, {"image": {"image": "a:b"}}) == want
        got = decode_message(schema, want)
        assert got["image"]["image"] == "a:b"

    def test_container_status_response_with_negative_exit(self):
        schema = MESSAGES["ContainerStatus"][1]
        obj = {"status": {"id": "c1", "state": "CONTAINER_EXITED",
                          "exit_code": -9}}
        raw = encode_message(schema, obj)
        # status = field 1 msg: id(1,str)="c1" -> 0a 02 63 31;
        # state(3,enum)=2 -> 18 02; exit_code(7,int)=-9 ->
        # 38 + ten-byte twos complement of -9
        inner = (b"\x0a\x02c1" + b"\x18\x02"
                 + b"\x38" + b"\xf7" + b"\xff" * 8 + b"\x01")
        assert raw == b"\x0a" + bytes([len(inner)]) + inner
        back = decode_message(schema, raw)
        assert back["status"]["state"] == "CONTAINER_EXITED"
        assert back["status"]["exit_code"] == -9

    def test_map_entry_layout(self):
        schema = MESSAGES["CreateContainer"][0]
        obj = {"config": {"labels": {"k": "v"}}}
        raw = encode_message(schema, obj)
        # config = field 2 msg -> key 0x12; labels = field 9 map ->
        # key 9<<3|2 = 0x4a; entry = key(1,str)"k" + value(2,str)"v"
        entry = b"\x0a\x01k\x12\x01v"
        labels = b"\x4a" + bytes([len(entry)]) + entry
        assert raw == b"\x12" + bytes([len(labels)]) + labels
        assert decode_message(schema, raw)["config"]["labels"] == \
            {"k": "v"}

    def test_repeated_strings(self):
        schema = MESSAGES["ImageStatus"][1]
        obj = {"image": {"id": "i", "repo_tags": ["a", "b"], "size": 5}}
        raw = encode_message(schema, obj)
        inner = (b"\x0a\x01i"            # id = field 1
                 + b"\x12\x01a\x12\x01b"  # repo_tags = field 2, twice
                 + b"\x20\x05")           # size = field 4 varint
        assert raw == b"\x0a" + bytes([len(inner)]) + inner
        back = decode_message(schema, raw)
        assert back["image"]["repo_tags"] == ["a", "b"]
        assert back["image"]["size"] == 5

    def test_filesystem_usage_nested(self):
        schema = MESSAGES["ImageFsInfo"][1]
        obj = {"image_filesystems": [{
            "timestamp": 7,
            "fs_id": {"mountpoint": "/tmp"},
            "used_bytes": {"value": 300},
            "inodes_used": {"value": 2}}]}
        raw = encode_message(schema, obj)
        fs = (b"\x08\x07"                        # timestamp = 1
              + b"\x12\x06\x0a\x04/tmp"          # fs_id.mountpoint
              + b"\x1a\x03\x08\xac\x02"          # used_bytes.value=300
              + b"\x22\x02\x08\x02")             # inodes_used.value=2
        assert raw == b"\x0a" + bytes([len(fs)]) + fs
        back = decode_message(schema, raw)
        assert back["image_filesystems"][0]["used_bytes"]["value"] == 300


class TestRobustness:
    def test_unknown_fields_skipped(self):
        schema = MESSAGES["Version"][1]
        known = encode_message(schema, {"runtime_name": "rt"})
        # splice in unknown field 99 (varint) and field 98 (len-delim)
        unknown = (encode_varint((99 << 3) | 0) + encode_varint(5)
                   + encode_varint((98 << 3) | 2) + b"\x03abc")
        back = decode_message(schema, unknown + known)
        assert back["runtime_name"] == "rt"

    def test_defaults_materialized(self):
        schema = MESSAGES["ImageStatus"][1]
        back = decode_message(schema, b"")
        assert back["image"] is None        # absent singular message
        assert back["info"] == {}           # absent map

    def test_info_map_json_values_roundtrip(self):
        schema = MESSAGES["CreateContainer"][1]
        obj = {"container_id": "c",
               "info": {"env": {"TPU_VISIBLE_CHIPS": "0,1"},
                        "pid": 42, "note": "plain"}}
        back = decode_message(schema, encode_message(schema, obj))
        assert back["info"]["env"] == {"TPU_VISIBLE_CHIPS": "0,1"}
        assert back["info"]["pid"] == 42
        assert back["info"]["note"] == "plain"

    def test_every_method_empty_roundtrip(self):
        """Each of the 12 verb pairs encodes/decodes an empty message
        (defaults materialize per schema, nothing raises)."""
        assert len(MESSAGES) == 12
        for method, (req, resp) in MESSAGES.items():
            for schema in (req, resp):
                assert decode_message(
                    schema, encode_message(schema, {})) is not None

    def test_truncated_field_raises(self):
        schema = MESSAGES["PullImage"][0]
        raw = encode_message(schema, {"image": {"image": "abc"}})
        with pytest.raises(ValueError):
            decode_message(schema, raw[:-1])

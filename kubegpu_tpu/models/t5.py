"""T5-style encoder-decoder family (TPU-native addition).

The third transformer family beyond decoder-only Llama and MoE:
bidirectional encoder + causal decoder with cross-attention, T5's
relative-position-bucket bias in place of rope, RMSNorm pre-norm, and
the T5.1.1 gated-GELU feed-forward.  Same house style as
:mod:`kubegpu_tpu.models.llama`: stacked-layer parameter pytrees
scanned with ``lax.scan``, GSPMD sharding specs (megatron tp on
heads/ffn, fsdp on the other dim), logical-sharding constraints so XLA
places the collectives.

Attention here is the XLA einsum path with an additive bias — the
pallas flash kernel has no bias hook, and the encoder/decoder lengths
of seq2seq workloads are short relative to the causal-LM bench; the
kernel stays the decoder-only families' specialty.

Reference note: the reference (SURVEY.md) is a scheduler with no model
code; this family exists to exercise the framework's workload surface
(encoder/decoder sharding, two-tower step) the way `example/` jobs
exercised the reference.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kubegpu_tpu.models.llama import _rmsnorm, embed_lookup
from kubegpu_tpu.ops.flash_attention import NEG_INF
from kubegpu_tpu.parallel.sharding import constrain


@dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 768
    n_enc_layers: int = 12
    n_dec_layers: int = 12
    n_heads: int = 12
    d_ff: int = 2048
    rel_buckets: int = 32
    rel_max_dist: int = 128
    norm_eps: float = 1e-6
    dtype: str = "bfloat16"
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def tiny(cls, **kw) -> "T5Config":
        base = cls(vocab_size=256, d_model=64, n_enc_layers=2,
                   n_dec_layers=2, n_heads=4, d_ff=128, rel_buckets=8,
                   rel_max_dist=32, dtype="float32")
        return replace(base, **kw)


# ---------------------------------------------------------------------------
# Init + sharding specs
# ---------------------------------------------------------------------------

def t5_init(key: jax.Array, cfg: T5Config) -> dict:
    hd = cfg.head_dim
    proj = cfg.n_heads * hd

    def norm_init(shape):
        return jnp.ones(shape, cfg.jdtype)

    def dense_init(k, shape, scale_dim):
        return (jax.random.normal(k, shape, jnp.float32)
                * (scale_dim ** -0.5)).astype(cfg.jdtype)

    def attn_block(k, n, prefix):
        ks = jax.random.split(k, 4)
        return {
            f"{prefix}q": dense_init(ks[0], (n, cfg.d_model, proj),
                                     cfg.d_model),
            f"{prefix}k": dense_init(ks[1], (n, cfg.d_model, proj),
                                     cfg.d_model),
            f"{prefix}v": dense_init(ks[2], (n, cfg.d_model, proj),
                                     cfg.d_model),
            f"{prefix}o": dense_init(ks[3], (n, proj, cfg.d_model), proj),
        }

    def ffn_block(k, n):
        ks = jax.random.split(k, 3)
        return {
            "wi_0": dense_init(ks[0], (n, cfg.d_model, cfg.d_ff),
                               cfg.d_model),
            "wi_1": dense_init(ks[1], (n, cfg.d_model, cfg.d_ff),
                               cfg.d_model),
            "wo_ff": dense_init(ks[2], (n, cfg.d_ff, cfg.d_model),
                                cfg.d_ff),
        }

    (k_emb, k_enc_a, k_enc_f, k_dec_s, k_dec_c, k_dec_f, k_out,
     k_enc_rel, k_dec_rel) = jax.random.split(key, 9)
    ne, nd = cfg.n_enc_layers, cfg.n_dec_layers
    return {
        "embed": dense_init(k_emb, (cfg.vocab_size, cfg.d_model),
                            cfg.d_model),
        # one shared bias table per stack ([buckets, H]), as in T5
        "enc_rel": dense_init(k_enc_rel, (cfg.rel_buckets, cfg.n_heads),
                              cfg.rel_buckets),
        "dec_rel": dense_init(k_dec_rel, (cfg.rel_buckets, cfg.n_heads),
                              cfg.rel_buckets),
        "encoder": {
            "attn_norm": norm_init((ne, cfg.d_model)),
            **attn_block(k_enc_a, ne, "w"),
            "mlp_norm": norm_init((ne, cfg.d_model)),
            **ffn_block(k_enc_f, ne),
        },
        "decoder": {
            "self_norm": norm_init((nd, cfg.d_model)),
            **attn_block(k_dec_s, nd, "s"),
            "cross_norm": norm_init((nd, cfg.d_model)),
            **attn_block(k_dec_c, nd, "c"),
            "mlp_norm": norm_init((nd, cfg.d_model)),
            **ffn_block(k_dec_f, nd),
        },
        "enc_final_norm": norm_init((cfg.d_model,)),
        "dec_final_norm": norm_init((cfg.d_model,)),
        "lm_head": dense_init(k_out, (cfg.d_model, cfg.vocab_size),
                              cfg.d_model),
    }


def t5_param_specs(cfg: T5Config) -> dict:
    def attn_specs(prefix):
        return {
            f"{prefix}q": P(None, "fsdp", "tp"),
            f"{prefix}k": P(None, "fsdp", "tp"),
            f"{prefix}v": P(None, "fsdp", "tp"),
            f"{prefix}o": P(None, "tp", "fsdp"),
        }

    ffn_specs = {
        "wi_0": P(None, "fsdp", "tp"),
        "wi_1": P(None, "fsdp", "tp"),
        "wo_ff": P(None, "tp", "fsdp"),
    }
    return {
        "embed": P("tp", "fsdp"),
        "enc_rel": P(None, "tp"),
        "dec_rel": P(None, "tp"),
        "encoder": {
            "attn_norm": P(None, None),
            **attn_specs("w"),
            "mlp_norm": P(None, None),
            **ffn_specs,
        },
        "decoder": {
            "self_norm": P(None, None),
            **attn_specs("s"),
            "cross_norm": P(None, None),
            **attn_specs("c"),
            "mlp_norm": P(None, None),
            **ffn_specs,
        },
        "enc_final_norm": P(None),
        "dec_final_norm": P(None),
        "lm_head": P("fsdp", "tp"),
    }


# ---------------------------------------------------------------------------
# Relative position bias (T5 bucketing)
# ---------------------------------------------------------------------------

def rel_pos_bucket(rel: jax.Array, bidirectional: bool,
                   num_buckets: int, max_dist: int) -> jax.Array:
    """T5's log-spaced relative-position bucketing.  ``rel`` is
    memory_pos - query_pos.  Bidirectional splits the bucket space by
    sign; causal buckets only the past (future clamps to bucket 0 but
    is masked anyway)."""
    ret = jnp.zeros_like(rel)
    if bidirectional:
        num_buckets //= 2
        ret = ret + (rel > 0).astype(rel.dtype) * num_buckets
        n = jnp.abs(rel)
    else:
        n = jnp.maximum(-rel, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_large = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / jnp.log(max_dist / max_exact)
        * (num_buckets - max_exact)).astype(rel.dtype)
    val_large = jnp.minimum(val_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_large)


def _rel_bias(table: jax.Array, t: int, s: int, bidirectional: bool,
              cfg: T5Config) -> jax.Array:
    """[H, T, S] additive attention bias from the [buckets, H] table."""
    q_pos = jnp.arange(t)[:, None]
    k_pos = jnp.arange(s)[None, :]
    bucket = rel_pos_bucket(k_pos - q_pos, bidirectional,
                            cfg.rel_buckets, cfg.rel_max_dist)
    return jnp.take(table, bucket, axis=0).transpose(2, 0, 1)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _bias_attention(q, k, v, bias, causal: bool) -> jax.Array:
    """q [B,T,H,D], k/v [B,S,H,D], bias [H,T,S] (or None) → [B,T,H,D].
    f32 scores/softmax, additive bias before masking."""
    d = q.shape[-1]
    scores = jnp.einsum("bthd,bshd->bhts", q, k,
                        preferred_element_type=jnp.float32) * d ** -0.5
    if bias is not None:
        scores = scores + bias[None].astype(jnp.float32)
    if causal:
        t, s = scores.shape[2], scores.shape[3]
        mask = jnp.tril(jnp.ones((t, s), bool), k=s - t)
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs, v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _attn(h, x, lp, prefix, cfg, bias, causal, mesh, kv_src=None):
    """Shared attention sublayer: norm'd input ``h`` projects q from
    itself and k/v from ``kv_src`` (cross-attention) or itself."""
    b, t = h.shape[0], h.shape[1]
    hd = cfg.head_dim
    src = h if kv_src is None else kv_src
    s = src.shape[1]
    q = (h @ lp[f"{prefix}q"]).reshape(b, t, cfg.n_heads, hd)
    k = (src @ lp[f"{prefix}k"]).reshape(b, s, cfg.n_heads, hd)
    v = (src @ lp[f"{prefix}v"]).reshape(b, s, cfg.n_heads, hd)
    o = _bias_attention(q, k, v, bias, causal)
    o = o.reshape(b, t, cfg.n_heads * hd)
    o = constrain(o, mesh, ("dp", "fsdp"), None, "tp")
    return x + (o @ lp[f"{prefix}o"]).astype(x.dtype)


def _ffn(x, lp, cfg, mesh):
    h = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
    up = jax.nn.gelu(h @ lp["wi_0"]) * (h @ lp["wi_1"])
    up = constrain(up, mesh, ("dp", "fsdp"), None, "tp")
    return x + (up @ lp["wo_ff"]).astype(x.dtype)


def t5_encode(params: dict, tokens: jax.Array, cfg: T5Config,
              mesh: Mesh | None = None) -> jax.Array:
    """tokens [B, S] → encoder states [B, S, d_model]."""
    x = embed_lookup(params["embed"], tokens, mesh)
    x = constrain(x, mesh, ("dp", "fsdp"), None, None)
    bias = _rel_bias(params["enc_rel"], tokens.shape[1], tokens.shape[1],
                     bidirectional=True, cfg=cfg)

    def layer(x, lp):
        h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        x = _attn(h, x, lp, "w", cfg, bias, causal=False, mesh=mesh)
        x = _ffn(x, lp, cfg, mesh)
        return constrain(x, mesh, ("dp", "fsdp"), None, None), None

    layer_fn = jax.checkpoint(layer) if cfg.remat else layer
    x, _ = lax.scan(layer_fn, x, params["encoder"])
    return _rmsnorm(x, params["enc_final_norm"], cfg.norm_eps)


def t5_decode_train(params: dict, enc_out: jax.Array,
                    dec_tokens: jax.Array, cfg: T5Config,
                    mesh: Mesh | None = None) -> jax.Array:
    """Teacher-forced decoder: [B, T] targets-in → logits [B, T, V]."""
    x = embed_lookup(params["embed"], dec_tokens, mesh)
    x = constrain(x, mesh, ("dp", "fsdp"), None, None)
    t = dec_tokens.shape[1]
    self_bias = _rel_bias(params["dec_rel"], t, t, bidirectional=False,
                          cfg=cfg)

    def layer(x, lp):
        h = _rmsnorm(x, lp["self_norm"], cfg.norm_eps)
        x = _attn(h, x, lp, "s", cfg, self_bias, causal=True, mesh=mesh)
        h = _rmsnorm(x, lp["cross_norm"], cfg.norm_eps)
        x = _attn(h, x, lp, "c", cfg, None, causal=False, mesh=mesh,
                  kv_src=enc_out)
        x = _ffn(x, lp, cfg, mesh)
        return constrain(x, mesh, ("dp", "fsdp"), None, None), None

    layer_fn = jax.checkpoint(layer) if cfg.remat else layer
    x, _ = lax.scan(layer_fn, x, params["decoder"])
    x = _rmsnorm(x, params["dec_final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return constrain(logits, mesh, ("dp", "fsdp"), None, "tp")


def t5_forward(params: dict, enc_tokens: jax.Array,
               dec_tokens: jax.Array, cfg: T5Config,
               mesh: Mesh | None = None) -> jax.Array:
    return t5_decode_train(params, t5_encode(params, enc_tokens, cfg,
                                             mesh),
                           dec_tokens, cfg, mesh)


def seq2seq_loss(params: dict, enc_tokens: jax.Array,
                 dec_tokens: jax.Array, cfg: T5Config,
                 mesh: Mesh | None = None) -> jax.Array:
    """Teacher-forced next-token loss on the decoder side: predict
    dec_tokens[:, 1:] from dec_tokens[:, :-1] given the encoded input."""
    logits = t5_forward(params, enc_tokens, dec_tokens[:, :-1], cfg,
                        mesh)
    targets = dec_tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -ll.mean()


def make_t5_train_step(cfg: T5Config, optimizer,
                       mesh: Mesh | None = None):
    """(params, opt_state, enc_tokens, dec_tokens) →
    (params, opt_state, loss); callers jit with their shardings."""
    import optax

    def step(params, opt_state, enc_tokens, dec_tokens):
        loss, grads = jax.value_and_grad(seq2seq_loss)(
            params, enc_tokens, dec_tokens, cfg, mesh)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss
    return step


# ---------------------------------------------------------------------------
# Serving: cached greedy decode (self-attn KV cache + precomputed
# cross-attention K/V)
# ---------------------------------------------------------------------------

def t5_cross_kv(params: dict, enc_out: jax.Array,
                cfg: T5Config) -> tuple[jax.Array, jax.Array]:
    """Cross-attention K/V projected ONCE from the encoder output (it
    never changes during decode — the classic enc-dec serving
    optimization).  Returns ([L, B, H, S_enc, hd], same for v)."""
    b = enc_out.shape[0]
    hd = cfg.head_dim
    nd = cfg.n_dec_layers

    def project(w):   # [L, D_model, H*hd] over enc_out [B, S, D_model]
        if hasattr(w, "dequantize"):
            # int8 weights (quantize_t5): einsum has no QTensor
            # overload — dequantize once here, at state init, not in
            # the per-step decode path
            w = w.dequantize(enc_out.dtype)
        y = jnp.einsum("bsd,ldh->lbsh", enc_out, w)
        return y.reshape(nd, b, enc_out.shape[1], cfg.n_heads, hd) \
                .transpose(0, 1, 3, 2, 4)      # [L, B, H, S_enc, hd]

    return project(params["decoder"]["ck"]), project(params["decoder"]["cv"])


def t5_init_decode_state(params: dict, enc_out: jax.Array,
                         cfg: T5Config, max_len: int) -> dict:
    """Decoder serving state: zeroed self-attn KV cache
    [L, B, H, max_len, D] plus the precomputed cross K/V."""
    b = enc_out.shape[0]
    ck, cv = t5_cross_kv(params, enc_out, cfg)
    shape = (cfg.n_dec_layers, b, cfg.n_heads, max_len, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.jdtype),
        "v": jnp.zeros(shape, cfg.jdtype),
        "cross_k": ck,
        "cross_v": cv,
    }


def _decode_rel_bias(table: jax.Array, pos, s: int,
                     cfg: T5Config) -> jax.Array:
    """[H, 1, S] causal rel-pos bias for a single query at ``pos``."""
    rel = jnp.arange(s) - pos                  # memory - query
    bucket = rel_pos_bucket(rel, False, cfg.rel_buckets,
                            cfg.rel_max_dist)
    return jnp.take(table, bucket, axis=0).T[:, None, :]   # [H, 1, S]


def t5_decode_step(params: dict, state: dict, token: jax.Array,
                   pos, cfg: T5Config) -> tuple[jax.Array, dict]:
    """One decoder token in, next-token logits out.  token: [B]; pos:
    scalar global decoder position of ``token``."""
    b = token.shape[0]
    hd = cfg.head_dim
    s = state["k"].shape[3]
    x = jnp.take(params["embed"], token[:, None], axis=0)  # [B, 1, D]
    self_bias = _decode_rel_bias(params["dec_rel"], pos, s, cfg)
    k_pos = jnp.arange(s)

    def layer(x, xs):
        lp, ck, cv, xk, xv = xs
        # self-attention over the cache (causal via k_pos <= pos)
        h = _rmsnorm(x, lp["self_norm"], cfg.norm_eps)
        q = (h @ lp["sq"]).reshape(b, 1, cfg.n_heads, hd)
        k = (h @ lp["sk"]).reshape(b, 1, cfg.n_heads, hd)
        v = (h @ lp["sv"]).reshape(b, 1, cfg.n_heads, hd)
        ck = lax.dynamic_update_slice(
            ck, k.transpose(0, 2, 1, 3).astype(ck.dtype),
            (0, 0, pos, 0))
        cv = lax.dynamic_update_slice(
            cv, v.transpose(0, 2, 1, 3).astype(cv.dtype),
            (0, 0, pos, 0))
        scores = jnp.einsum("bthd,bhsd->bhts", q, ck,
                            preferred_element_type=jnp.float32) \
            * hd ** -0.5
        scores = scores + self_bias[None].astype(jnp.float32)
        scores = jnp.where((k_pos <= pos)[None, None, None],
                           scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bhts,bhsd->bthd", probs, cv,
                       preferred_element_type=jnp.float32)
        o = o.astype(x.dtype).reshape(b, 1, cfg.n_heads * hd)
        x = x + (o @ lp["so"]).astype(x.dtype)
        # cross-attention over the precomputed encoder K/V (no bias)
        h = _rmsnorm(x, lp["cross_norm"], cfg.norm_eps)
        q = (h @ lp["cq"]).reshape(b, 1, cfg.n_heads, hd)
        scores = jnp.einsum("bthd,bhsd->bhts", q, xk,
                            preferred_element_type=jnp.float32) \
            * hd ** -0.5
        probs = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bhts,bhsd->bthd", probs, xv,
                       preferred_element_type=jnp.float32)
        o = o.astype(x.dtype).reshape(b, 1, cfg.n_heads * hd)
        x = x + (o @ lp["co"]).astype(x.dtype)
        x = _ffn(x, lp, cfg, None)
        return x, (ck, cv)

    x, (ck_new, cv_new) = lax.scan(
        layer, x, (params["decoder"], state["k"], state["v"],
                   state["cross_k"], state["cross_v"]))
    x = _rmsnorm(x, params["dec_final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    state = {**state, "k": ck_new, "v": cv_new}
    return logits[:, 0], state


@functools.lru_cache(maxsize=16)
def _t5_generate_fn(cfg: T5Config, s_enc: int, n_steps: int,
                    max_len: int):
    @jax.jit
    def run(params, enc_tokens, start_token):
        enc_out = t5_encode(params, enc_tokens, cfg)
        state = t5_init_decode_state(params, enc_out, cfg, max_len)

        def step(carry, i):
            token, state = carry
            logits, state = t5_decode_step(params, state, token, i, cfg)
            nxt = jnp.argmax(logits, axis=-1).astype(token.dtype)
            return (nxt, state), nxt

        (_, _), toks = lax.scan(
            step, (start_token, state), jnp.arange(n_steps))
        return toks.swapaxes(0, 1)     # [B, n_steps]

    return run


def _t5_buffer_partials(q0: jax.Array, bk: jax.Array, bv: jax.Array,
                        j: jax.Array, bias: jax.Array
                        ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Biased softmax partials over the in-block write buffer (valid at
    index <= j).  q0: [B, H, hd]; buffer [B, H, stride, hd]; bias
    [H, stride] (T5 rel-pos, precomputed — buffer key j' sits at
    relative offset j' - j regardless of the global position)."""
    hd = q0.shape[-1]
    stride = bk.shape[2]
    s = jnp.einsum("bhd,bhsd->bhs", q0, bk.astype(q0.dtype),
                   preferred_element_type=jnp.float32) * (hd ** -0.5)
    s = s + bias[None].astype(jnp.float32)
    mask = (jnp.arange(stride) <= j)[None, None, :]
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    w = jnp.where(mask, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(w, axis=-1)
    o = jnp.einsum("bhs,bhsd->bhd", w.astype(bv.dtype), bv,
                   preferred_element_type=jnp.float32)
    return o / jnp.maximum(l, 1e-30)[..., None], m, l


def _t5_paged_step(params: dict, token: jax.Array, pool_k, pool_v,
                   pt: jax.Array, d0: jax.Array, buf_k, buf_v,
                   pos, j, cfg: T5Config, interpret: bool):
    """One T5 decoder token with the flushed self-attn history on the
    page pool (read by :func:`paged_attention_biased`, which computes
    the causal rel-pos bias in-kernel) and this block's keys in a
    dense write buffer.  token: [B]; pos: global decoder position;
    j: in-block index.  Returns (logits [B, V], buf_k', buf_v')."""
    from kubegpu_tpu.ops.paged_attention import (
        merge_partials,
        paged_attention_biased,
    )
    b = token.shape[0]
    hd = cfg.head_dim
    stride = buf_k.shape[3]
    x = jnp.take(params["embed"], token[:, None], axis=0)  # [B, 1, D]
    table = params["dec_rel"]                    # [n_buckets, H]
    nb = table.shape[0]
    # buffer key j' sits at global pos (pos - j + j'): rel = j' - j
    buf_bucket = rel_pos_bucket(jnp.arange(stride) - j, False, nb,
                                cfg.rel_max_dist)
    buf_bias = jnp.take(table, buf_bucket, axis=0).T     # [H, stride]
    qpos = jnp.full((b,), pos, jnp.int32)
    zeros_b = jnp.zeros((b,), jnp.int32)
    lidx = jnp.arange(cfg.n_dec_layers, dtype=jnp.int32)

    def layer(x, xs):
        lp, xk, xv, bk, bv, li = xs
        h = _rmsnorm(x, lp["self_norm"], cfg.norm_eps)
        q = (h @ lp["sq"]).reshape(b, 1, cfg.n_heads, hd) \
            .transpose(0, 2, 1, 3)                       # [B, H, 1, hd]
        k = (h @ lp["sk"]).reshape(b, 1, cfg.n_heads, hd) \
            .transpose(0, 2, 1, 3)
        v = (h @ lp["sv"]).reshape(b, 1, cfg.n_heads, hd) \
            .transpose(0, 2, 1, 3)
        bk = lax.dynamic_update_slice(bk, k.astype(bk.dtype),
                                      (0, 0, j, 0))
        bv = lax.dynamic_update_slice(bv, v.astype(bv.dtype),
                                      (0, 0, j, 0))
        q0 = q[:, :, 0, :]
        o_p, m_p, l_p = paged_attention_biased(
            q0, pool_k, pool_v, pt, li, zeros_b, zeros_b, d0, qpos,
            table.T, bias_max_dist=cfg.rel_max_dist,
            interpret=interpret)
        o_b, m_b, l_b = _t5_buffer_partials(q0, bk, bv, j, buf_bias)
        o = merge_partials(o_p, m_p, l_p, o_b, m_b, l_b)
        o = o.astype(x.dtype).reshape(b, 1, cfg.n_heads * hd)
        x = x + (o @ lp["so"]).astype(x.dtype)
        # cross-attention over the precomputed encoder K/V (no bias)
        h = _rmsnorm(x, lp["cross_norm"], cfg.norm_eps)
        cq = (h @ lp["cq"]).reshape(b, 1, cfg.n_heads, hd)
        scores = jnp.einsum("bthd,bhsd->bhts", cq, xk,
                            preferred_element_type=jnp.float32) \
            * hd ** -0.5
        probs = jax.nn.softmax(scores, axis=-1)
        co = jnp.einsum("bhts,bhsd->bthd", probs, xv,
                        preferred_element_type=jnp.float32)
        co = co.astype(x.dtype).reshape(b, 1, cfg.n_heads * hd)
        x = x + (co @ lp["co"]).astype(x.dtype)
        x = _ffn(x, lp, cfg, None)
        return x, (bk, bv)

    x, (bk_new, bv_new) = lax.scan(
        layer, x, (params["decoder"], params["_cross_k"],
                   params["_cross_v"], buf_k, buf_v, lidx))
    x = _rmsnorm(x, params["dec_final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits[:, 0], bk_new, bv_new


@functools.lru_cache(maxsize=8)
def _t5_paged_generate_fn(cfg: T5Config, s_enc: int, n_steps: int,
                          page_size: int, interpret: bool):
    """T5 generation with the decoder self-attn cache on a page pool
    (VERDICT r4 weak #6: T5 was stuck on the dense per-slot cache).
    Outer scan over page-sized blocks (flush once per full page —
    stride == page_size, so a block IS a page), inner scan over the
    block's steps with the dense write buffer; the flushed history is
    read by the biased paged kernel."""
    stride = page_size
    n_blocks = -(-n_steps // stride)

    @jax.jit
    def run(params, enc_tokens, start_token):
        enc_out = t5_encode(params, enc_tokens, cfg)
        b = enc_out.shape[0]
        hd = cfg.head_dim
        nd = cfg.n_dec_layers
        ck, cv = t5_cross_kv(params, enc_out, cfg)
        # cross K/V ride the params pytree into the step (the layer
        # scan slices them per layer); pool pages are per-row static
        p_aug = {**params, "_cross_k": ck, "_cross_v": cv}
        pool_shape = (nd, 1 + b * n_blocks, cfg.n_heads, page_size, hd)
        pool_k = jnp.zeros(pool_shape, cfg.jdtype)
        pool_v = jnp.zeros(pool_shape, cfg.jdtype)
        pt = (1 + jnp.arange(b)[:, None] * n_blocks
              + jnp.arange(n_blocks)[None, :]).astype(jnp.int32)

        def block(carry, bi):
            token, pool_k, pool_v, out = carry
            d0 = jnp.full((b,), bi * stride, jnp.int32)
            buf_k = jnp.zeros((nd, b, cfg.n_heads, stride, hd),
                              cfg.jdtype)
            buf_v = jnp.zeros_like(buf_k)

            def step(c2, j):
                token, buf_k, buf_v, out = c2
                pos = bi * stride + j
                logits, buf_k, buf_v = _t5_paged_step(
                    p_aug, token, pool_k, pool_v, pt, d0, buf_k,
                    buf_v, pos, j, cfg, interpret)
                nxt = jnp.argmax(logits, axis=-1).astype(token.dtype)
                out = lax.dynamic_update_slice(out, nxt[:, None],
                                               (0, pos))
                return (nxt, buf_k, buf_v, out), None

            (token, buf_k, buf_v, out), _ = lax.scan(
                step, (token, buf_k, buf_v, out), jnp.arange(stride))
            # flush the full page into each row's page ``bi``
            def write_row(r, kv):
                pk, pv = kv
                start = (0, pt[r, bi], 0, 0, 0)
                pk = lax.dynamic_update_slice(
                    pk, lax.dynamic_slice_in_dim(buf_k, r, 1, axis=1),
                    start)
                pv = lax.dynamic_update_slice(
                    pv, lax.dynamic_slice_in_dim(buf_v, r, 1, axis=1),
                    start)
                return pk, pv

            pool_k, pool_v = lax.fori_loop(0, b, write_row,
                                           (pool_k, pool_v))
            return (token, pool_k, pool_v, out), None

        out0 = jnp.zeros((b, n_blocks * stride), jnp.int32)
        (tok, pool_k, pool_v, out), _ = lax.scan(
            block, (start_token, pool_k, pool_v, out0),
            jnp.arange(n_blocks))
        return out[:, :n_steps]

    return run


def t5_greedy_generate_paged(params: dict, enc_tokens: jax.Array,
                             n_steps: int, cfg: T5Config,
                             start_token: int = 0,
                             page_size: int = 128) -> jax.Array:
    """:func:`t5_greedy_generate` with the decoder self-attn cache in
    a page pool read by the biased paged-attention kernel.  Same
    return contract; cross-attention stays dense (encoder activations,
    not KV cache)."""
    if n_steps < 1:
        raise ValueError(f"n_steps must be >= 1, got {n_steps}")
    start = jnp.full((enc_tokens.shape[0],), start_token, jnp.int32)
    interpret = jax.devices()[0].platform == "cpu"
    return _t5_paged_generate_fn(
        cfg, enc_tokens.shape[1], n_steps, page_size, interpret)(
        params, enc_tokens, start)


def t5_greedy_generate(params: dict, enc_tokens: jax.Array,
                       n_steps: int, cfg: T5Config,
                       start_token: int = 0,
                       max_len: int | None = None) -> jax.Array:
    """Encoder-decoder greedy generation: encode once, precompute the
    cross K/V, then one scanned decode loop from ``start_token`` (T5's
    decoder-start convention, default id 0).  Returns [B, n_steps]."""
    max_len = max_len or n_steps
    if n_steps < 1:
        raise ValueError(f"n_steps must be >= 1, got {n_steps}")
    if n_steps > max_len:
        raise ValueError(f"n_steps {n_steps} > max_len {max_len}")
    start = jnp.full((enc_tokens.shape[0],), start_token, jnp.int32)
    return _t5_generate_fn(cfg, enc_tokens.shape[1], n_steps, max_len)(
        params, enc_tokens, start)

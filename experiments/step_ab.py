"""Same-window A/B/A of the flagship train step over a kernel knob.

Usage: step_ab.py [knob value knob value ...] — e.g.
    step_ab.py DKV_GROUPED_BQ_CAP 256 DKV_GROUPED_BQ_CAP 512 \
               DKV_GROUPED_BQ_CAP 256

Each leg sets the flash_attention module constant, clears ALL jit
caches (the custom-vjp's inner jit would otherwise replay the previous
leg's trace — module constants are trace-time), compiles the step
(retrying the tunnel's flaky remote-compile helper), and times 12
chained iterations.  The bracket (A...A) bounds window drift."""

import importlib
import sys
import time

sys.path.insert(0, "/root/repo")

import jax                                      # noqa: E402
import jax.numpy as jnp                         # noqa: E402
import numpy as np                              # noqa: E402
import optax                                    # noqa: E402

from kubegpu_tpu.benchmark import (             # noqa: E402
    _time_chained,
    chip_peak_tflops,
    llama_bench_config,
    train_flops_per_step,
)
from kubegpu_tpu.models import llama_init       # noqa: E402
from kubegpu_tpu.models.llama import make_train_step  # noqa: E402

fa = importlib.import_module("kubegpu_tpu.ops.flash_attention")


def one_leg(cfg, batch, seq, knob, value):
    setattr(fa, knob, value)
    jax.clear_caches()
    params = llama_init(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)
    step = jax.jit(make_train_step(cfg, opt), donate_argnums=(0, 1))
    tokens = jnp.asarray(
        (np.arange(batch * seq).reshape(batch, seq)) % cfg.vocab_size,
        jnp.int32)
    for attempt in range(4):   # remote-compile helper is flaky
        try:
            step_s, state = _time_chained(
                lambda s: step(s[0], s[1], tokens),
                (params, opt_state), iters=12)
            del state
            break
        except Exception as e:
            if attempt == 3:
                raise
            print(f"  compile retry {attempt+1}: {str(e)[:90]}",
                  flush=True)
            time.sleep(5)
    flops = train_flops_per_step(cfg, batch, seq)
    peak = chip_peak_tflops(jax.devices()[0])
    mfu = flops / step_s / (peak * 1e12)
    print(f"{knob}={value}: step {step_s*1e3:8.2f} ms  "
          f"MFU {mfu:.4f}", flush=True)
    return step_s


def main():
    args = sys.argv[1:] or ["DKV_GROUPED_BQ_CAP", "256",
                            "DKV_GROUPED_BQ_CAP", "512",
                            "DKV_GROUPED_BQ_CAP", "256"]
    legs = [(args[i], int(args[i + 1])) for i in range(0, len(args), 2)]
    cfg = llama_bench_config()
    for knob, value in legs:
        one_leg(cfg, 4, 2048, knob, value)


if __name__ == "__main__":
    main()

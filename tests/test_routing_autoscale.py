"""Prefix-affinity routing + SLO-driven autoscaling (ISSUE 14).

The routing half: the pool router scores replicas by (pages of this
prompt's chain already resident) minus the least-loaded penalty,
entirely host-side — same seeded traffic must route identically run
to run, zero-affinity traffic must route BIT-IDENTICALLY to the
least-loaded policy, and a chain's home replica must win the routing
argument until real load outweighs it.

The scaling half: ``retire_replica`` drains through the bit-exact
replay parking without burning any request's bounded failover budget,
``add_replica`` grows the pool onto spare device blocks through the
same construction path as ``__init__``, and ``ServingAutoscaler``
closes the loop against a live ``SimCluster`` — gang spawned through
the extender on the way up, gang evicted (requeue=False) behind the
drain on the way down."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubegpu_tpu.models import LlamaConfig, greedy_generate, llama_init
from kubegpu_tpu.models.serve import DataParallelServePool
from kubegpu_tpu.obs.metrics import MetricsRegistry
from kubegpu_tpu.scheduler.serve import (
    AutoscaleConfig,
    AutoscalePolicy,
    ServingAutoscaler,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(max_seq_len=64)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    return cfg, params


def solo(params, prompt, n, cfg):
    out = greedy_generate(params, jnp.asarray(prompt, jnp.int32)[None],
                          n, cfg, max_len=cfg.max_seq_len)
    return [int(t) for t in np.asarray(out)[0]]


def _pool(params, cfg, routing="affinity", metrics=None, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("stride", 2)
    kw.setdefault("prompt_buckets", (8, 24))
    kw.setdefault("page_size", 8)
    kw.setdefault("prefix_cache", True)
    return DataParallelServePool(params, cfg, dp=2, tp=1,
                                 routing=routing, metrics=metrics, **kw)


def _chain_prompt(rng, lead, t, vocab):
    """A ``t``-token prompt whose first ``len(lead)`` tokens are the
    shared chain (page-aligned lead ⇒ hashable whole pages)."""
    tail = rng.integers(1, vocab, t - len(lead)).tolist()
    return list(lead) + tail


class TestAffinityRouting:

    def test_same_trace_routes_identically(self, tiny):
        """Seeded determinism: the router is pure host arithmetic over
        the digest + load state, so one trace yields ONE route log."""
        cfg, params = tiny
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        rng = np.random.default_rng(3)
        lead = rng.integers(1, 32, 16).tolist()
        trace = [(_chain_prompt(rng, lead, 20, 32), 4)
                 for _ in range(6)]

        def run():
            pool = _pool(params, cfg)
            for p, n in trace:
                pool.submit(p, n)
            log = list(pool.route_log)
            done = {r.rid: r for r in pool.drain()}
            return log, done

        log_a, done_a = run()
        log_b, done_b = run()
        assert log_a == log_b
        assert {rid: r.tokens for rid, r in done_a.items()} \
            == {rid: r.tokens for rid, r in done_b.items()}

    def test_chain_pulls_to_home_replica_until_load_dominates(
            self, tiny):
        """A 2-page chain resident only on replica 0 pulls same-chain
        traffic there past a 1-request load gap (affinity 2 beats
        load 1), but NOT past a gap wider than the chain (the
        least-loaded penalty must stay in charge of overload)."""
        cfg, params = tiny
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        rng = np.random.default_rng(5)
        lead = rng.integers(1, 32, 16).tolist()   # 2 whole pages
        pool = _pool(params, cfg)
        p0 = _chain_prompt(rng, lead, 20, 32)
        pool.submit(p0, 6)                # ties → replica 0 (index)
        pool.submit(_chain_prompt(rng, lead, 20, 32), 6)
        pool.submit(_chain_prompt(rng, lead, 20, 32), 6)
        # digest warm-add at submit keeps the same-tick burst together:
        # affinity 2 offsets replica 0's growing queue for one extra
        # request, then the load gap (2 vs 0) dominates and the router
        # falls back to the idle replica
        assert [rep for _, rep, _ in pool.route_log] == [0, 0, 1]
        assert [aff for _, _, aff in pool.route_log] == [0, 2, 0]
        assert pool.routing_affinity_hits == 1
        for r in pool.drain():
            assert r.error is None

    def test_zero_affinity_is_bit_identical_to_least_loaded(self, tiny):
        """Prompts with no cacheable whole page (t <= page_size) have
        no chain keys: the affinity score degenerates to exactly the
        least-loaded key, so the two policies route — and emit —
        identically."""
        cfg, params = tiny
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        rng = np.random.default_rng(9)
        trace = [(rng.integers(1, 32, int(rng.integers(3, 8))).tolist(),
                  int(rng.integers(2, 6))) for _ in range(8)]

        def run(routing):
            pool = _pool(params, cfg, routing=routing)
            for p, n in trace:
                pool.submit(p, n)
            log = [(rid, rep) for rid, rep, _ in pool.route_log]
            toks = {r.rid: r.tokens for r in pool.drain()}
            return log, toks

        log_aff, toks_aff = run("affinity")
        log_ll, toks_ll = run("least_loaded")
        assert log_aff == log_ll
        assert toks_aff == toks_ll

    def test_admission_queue_token_counter_invariant(self, tiny):
        """The router's prefill-backlog tiebreak reads the admission
        queue's incrementally-maintained token total — it must agree
        with a full scan through submit/admit/finish churn."""
        cfg, params = tiny
        pool = DataParallelServePool(params, cfg, dp=1, tp=1,
                                     n_slots=1, stride=2,
                                     prompt_buckets=(8,), page_size=8)
        eng = pool.replicas[0]

        def check():
            assert eng.queue.prompt_tokens \
                == sum(r.prompt_len for r, _ in eng.queue)

        rng = np.random.default_rng(1)
        for k in range(5):
            pool.submit(rng.integers(1, 32, 3 + k).tolist(), 3)
            check()
        for _ in range(40):
            pool.step()
            check()
            if not eng.queue and not eng.slot_req:
                break
        assert eng.queue.prompt_tokens == 0


class TestScaleSurface:

    def test_retire_replica_drains_bit_exact_without_burning_retries(
            self, tiny):
        """Graceful scale-down: residents replay onto survivors
        bit-exactly, exactly once, the drain never counts as a
        failover or burns a request's bounded replay budget, and the
        retired replica's queue-depth gauge is deleted."""
        cfg, params = tiny
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        reg = MetricsRegistry()
        pool = _pool(params, cfg, metrics=reg)
        rng = np.random.default_rng(7)
        work = [(rng.integers(1, 32, 6).tolist(), 8) for _ in range(4)]
        rids = {pool.submit(p, n): (p, n) for p, n in work}
        done = {}
        for _ in range(2):
            for r in pool.step():
                done[r.rid] = r
        assert "serve_replica_queue_depth_r0" \
            in reg.snapshot()["gauges"]

        pool.retire_replica(0)
        for r in pool.drain():
            assert r.rid not in done
            done[r.rid] = r
        assert set(done) == set(rids)
        for rid, (p, n) in rids.items():
            assert done[rid].error is None, (rid, done[rid].error)
            assert done[rid].tokens == solo(params, p, n, cfg), rid
        assert 0 in pool.dead_replicas
        assert pool.drains == 1 and pool.drain_replays >= 1
        assert pool.failovers == 0          # a drain is not a fault
        assert pool.requests_retried == 0   # budget untouched
        gauges = reg.snapshot()["gauges"]
        assert "serve_replica_queue_depth_r0" not in gauges
        assert gauges["serve_replicas_active"] == 1.0
        with pytest.raises(ValueError):
            pool.retire_replica(1)          # never the last replica

    def test_add_replica_grows_pool_and_exhausts_devices(self, tiny):
        cfg, params = tiny
        if len(jax.devices()) < 3:
            pytest.skip("needs 3 devices")
        pool = DataParallelServePool(
            params, cfg, dp=2, tp=1, devices=jax.devices()[:3],
            n_slots=2, stride=2, prompt_buckets=(8,), page_size=8)
        i = pool.add_replica()
        assert i == 2 and pool.dp == 3
        assert len(pool._alive()) == 3
        assert pool.replicas_active_max == 3
        # the new replica serves real traffic through the router
        rng = np.random.default_rng(2)
        work = [(rng.integers(1, 32, 5).tolist(), 4) for _ in range(6)]
        rids = {pool.submit(p, n): (p, n) for p, n in work}
        assert {rep for _, rep, _ in pool.route_log} == {0, 1, 2}
        for r in pool.drain():
            p, n = rids[r.rid]
            assert r.tokens == solo(params, p, n, cfg)
        with pytest.raises(ValueError, match="no spare devices"):
            pool.add_replica()              # 3 devices, 3 live replicas


class TestAutoscalePolicy:

    def test_hysteresis_and_cooldown_are_deterministic(self):
        cfg = AutoscaleConfig(min_replicas=1, max_replicas=4,
                              queue_wait_high_ticks=4.0, hold_ticks=2,
                              idle_ticks=3, cooldown_ticks=4,
                              seed=13, cooldown_jitter_ticks=2)

        def run():
            pol = AutoscalePolicy(cfg)
            acts = [pol.decide(t, 2, queue_wait_ticks=10.0,
                               attainment=1.0) for t in range(6)]
            acts += [pol.decide(t, 2, queue_wait_ticks=0.0,
                                attainment=1.0) for t in range(6, 20)]
            return acts, pol.decisions

        a1, d1 = run()
        a2, d2 = run()
        assert a1 == a2 and d1 == d2            # seeded jitter included
        # +1 only after hold_ticks of pressure, -1 only after
        # idle_ticks of calm, and EVERY pair of consecutive actions
        # at least the (jittered ≥ base) cooldown apart
        assert a1[0] == 0 and a1[1] == 1
        assert -1 in a1[6:]
        ticks = [t for t, _ in d1]
        assert all(b - a >= cfg.cooldown_ticks
                   for a, b in zip(ticks, ticks[1:]))
        first_down = min(t for t, act in d1 if act == -1)
        # the down needed idle_ticks of calm AFTER the pressure phase
        assert first_down >= 6 + cfg.idle_ticks - 1

    def test_replica_bounds_clamp_actions(self):
        pol = AutoscalePolicy(AutoscaleConfig(
            min_replicas=1, max_replicas=2, hold_ticks=1,
            idle_ticks=1, cooldown_ticks=0))
        assert pol.decide(0, 2, queue_wait_ticks=99.0,
                          attainment=0.0) == 0   # already at max
        assert pol.decide(1, 1, queue_wait_ticks=0.0,
                          attainment=1.0) == 0   # already at min


class TestAutoscalerControlPlane:

    def test_scale_cycle_through_extender_gang_path(self, tiny):
        """ServingAutoscaler against a live SimCluster: pressure spawns
        a serving gang through the extender and binds the new replica;
        calm retires the highest-index replica (drain via replay
        parking) and evicts its gang without requeue — the health
        watch sees the eviction land on an already-drained replica."""
        from kubegpu_tpu.cluster import SimCluster

        cfg, params = tiny
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        cl = SimCluster(["v5e-16"])
        try:
            names = cl.scheduler.spawn_serving_gang("serve-base",
                                                    chips=1)
            assert names == ["serve-base-0"]
            pool = DataParallelServePool(
                params, cfg, dp=1, tp=1, devices=jax.devices(),
                n_slots=2, stride=2, prompt_buckets=(8,),
                page_size=8, metrics=cl.metrics)
            pool.bind_replica_gang(0, "serve-base")
            pool.watch_health(cl.api)
            scaler = ServingAutoscaler(
                pool, AutoscalePolicy(AutoscaleConfig(
                    min_replicas=1, max_replicas=2,
                    queue_wait_high_ticks=2.0, hold_ticks=1,
                    idle_ticks=2, cooldown_ticks=2)),
                scheduler=cl.scheduler, cluster=cl,
                chips_per_replica=1)

            rng = np.random.default_rng(4)
            work = [(rng.integers(1, 32, 6).tolist(), 6)
                    for _ in range(6)]
            rids = {pool.submit(p, n): (p, n) for p, n in work}
            done = {}
            tick = 0
            while not scaler.scale_ups and tick < 50:
                for r in pool.step():
                    done[r.rid] = r
                scaler(tick, {"attainment": 1.0})
                tick += 1
            assert scaler.scale_ups == 1
            assert pool._gang_replica.get("serve-asg0") == 1
            # the gang really went through the apiserver + extender
            assert cl.api.get("Pod", "serve-asg0-0") is not None

            # once the queue empties the calm ticks accumulate and the
            # policy shrinks back — keep the controller in the loop
            # while the remaining work drains
            while not scaler.scale_downs and tick < 250:
                for r in pool.step():
                    done[r.rid] = r
                scaler(tick, {"attainment": 1.0})
                tick += 1
            assert scaler.scale_downs == 1
            for r in pool.step():     # the retire lands next step
                done[r.rid] = r
            assert 1 in pool.dead_replicas
            assert pool.drains == 1
            # the gang's pods were torn down WITHOUT requeue — the
            # scale-down is an intentional shrink, not a fault to heal
            from kubegpu_tpu.kubemeta.controlplane import NotFound
            with pytest.raises(NotFound):
                cl.api.get("Pod", "serve-asg0-0")
            cl.step()     # watch-delivered eviction: already drained
            for r in pool.drain():
                done[r.rid] = r
            assert set(done) == set(rids)
            for rid, (p, n) in rids.items():
                assert done[rid].error is None
                assert done[rid].tokens == solo(params, p, n, cfg)
            assert pool.failovers == 0
            assert pool.replicas_active_min == 1
            assert pool.replicas_active_max == 2
            # the pool keeps serving on the surviving replica
            p, n = work[0]
            rid = pool.submit(p, n)
            out = {r.rid: r for r in pool.drain()}
            assert out[rid].tokens == solo(params, p, n, cfg)
            pool.close()
        finally:
            cl.close()

"""Fleet-scale robustness (ISSUE 19): the REAL serving control plane
over simulated cost-model replicas — correlated failure-domain chaos,
health-watch delivery weather, rolling upgrade waves, and
control-plane crash recovery from an append-only journal.

The contract under EVERY scenario: no admitted request is lost, none
completes twice, tier ordering never inverts, and every scenario
run's per-request outcomes are identical to an uninterrupted twin —
all deterministic by seed, no real accelerator involved."""

import numpy as np
import pytest

from kubegpu_tpu.fleet import (
    ControlPlaneJournal,
    FleetConfig,
    FleetDisaggPool,
    FleetPool,
    FleetTopology,
    ReplicaCosts,
    SimReplicaEngine,
    UpgradeWaveController,
    compare_outcomes,
    run_fleet,
)
from kubegpu_tpu.loadgen import LoadSpec, TierSpec, synth_trace
from kubegpu_tpu.obs.chaos import (
    DOMAIN_EVICT,
    DOMAIN_KILL,
    KILL,
    WATCH_DELAY,
    WATCH_DUP,
    WATCH_PARTITION,
    WATCH_REORDER,
    ChaosEvent,
    ChaosInjector,
    DomainChaosEvent,
    DomainChaosInjector,
)
from kubegpu_tpu.obs.metrics import MetricsRegistry

TIERS = (TierSpec("gold", ttft_slo_ticks=40, token_slo_ticks=40.0,
                  share=0.2),
         TierSpec("silver", ttft_slo_ticks=80, token_slo_ticks=80.0,
                  share=0.3),
         TierSpec("bronze", ttft_slo_ticks=10**6,
                  token_slo_ticks=1e6, share=0.5))


def mk_trace(n=96, seed=1907):
    return synth_trace(LoadSpec(
        seed=seed, n_requests=n, mean_iat_ticks=0.25, tiers=TIERS,
        diurnal=True, flash_at=(10.0,), flash_rate_x=4.0,
        flash_len_ticks=8.0))


def drain_engine(eng):
    out = []
    while eng.slot_req or eng.queue:
        out.extend(eng.step())
    return out


# -- the simulated engine ----------------------------------------------

class TestSimEngine:
    def test_tokens_deterministic_pure_function_of_sequence(self):
        a, b = SimReplicaEngine(FleetConfig()), SimReplicaEngine(
            FleetConfig())
        pa = a.submit([3, 5, 7], 6)
        pb = b.submit([3, 5, 7], 6)
        ra = {r.rid: r for r in drain_engine(a)}[pa]
        rb = {r.rid: r for r in drain_engine(b)}[pb]
        assert ra.tokens == rb.tokens
        assert len(ra.tokens) == 6
        assert all(1 <= t < FleetConfig().vocab for t in ra.tokens)

    def test_replay_as_prompt_plus_accepted_is_bit_exact(self):
        ref = SimReplicaEngine(FleetConfig())
        full_rid = ref.submit([3, 5, 7], 8)
        full = {r.rid: r for r in drain_engine(ref)}[full_rid]
        # interrupt after 3 tokens, replay prompt ++ accepted — the
        # crc32 running state resumes exactly (the property every
        # failover / preemption / migration replay leans on)
        head = full.tokens[:3]
        eng = SimReplicaEngine(FleetConfig())
        replay = np.concatenate(
            [np.asarray([3, 5, 7], np.int32),
             np.asarray(head, np.int32)])
        rid = eng.submit(replay, 5)
        tail = {r.rid: r for r in drain_engine(eng)}[rid]
        assert head + tail.tokens == full.tokens

    def test_strict_tier_admission_no_inversion(self):
        cfg = FleetConfig(n_slots=1)
        eng = SimReplicaEngine(cfg)
        eng.submit([2, 2], 2, tier=2)
        eng.submit([3, 3], 2, tier=0)
        eng.submit([4, 4], 2, tier=1)
        drain_engine(eng)
        tiers_in_order = [t for _, t, _ in eng.admission_log]
        assert tiers_in_order == sorted(tiers_in_order)
        assert eng.tier_inversions == 0

    def test_prefix_registry_shortens_prefill(self):
        cfg = FleetConfig(page_size=4, prefill_tokens_per_tick=4)
        eng = SimReplicaEngine(cfg)
        prompt = list(range(1, 17))
        r1 = eng.submit(prompt, 2)
        first = {r.rid: r for r in drain_engine(eng)}[r1]
        r2 = eng.submit(prompt, 2)
        second = {r.rid: r for r in drain_engine(eng)}[r2]
        assert second.tokens == first.tokens
        cold_ttft = first.first_tick - first.submit_tick
        warm_ttft = second.first_tick - second.submit_tick
        assert warm_ttft < cold_ttft

    def test_kill_stashes_finishers_as_orphans(self):
        from kubegpu_tpu.obs.chaos import ReplicaDeadError
        eng = SimReplicaEngine(
            FleetConfig(),
            chaos=ChaosInjector(events=[ChaosEvent(tick=1,
                                                   kind=KILL)]))
        eng.submit([5, 5], 2)     # finishes ON the dying tick
        eng.step()                # admit + prefill + first token
        with pytest.raises(ReplicaDeadError):
            eng.step()            # second token, then the kill lands
        assert eng.dead is not None
        # the dying tick's finisher went to the orphan stash, so the
        # pool's failover must never replay a completed request
        done = [r for r in eng.take_orphans() if r.done]
        assert len(done) == 1 and len(done[0].tokens) == 2

    def test_bench_calibration_reads_rows_or_defaults(self):
        c = ReplicaCosts.from_bench(root="/nonexistent")
        assert c.block_ms == ReplicaCosts.block_ms
        c2 = ReplicaCosts.from_bench()
        assert c2.block_ms > 0 and c2.prefill_ms_per_token > 0


# -- the real pool over sim engines ------------------------------------

class TestFleetPool:
    def test_failover_exactly_once_bit_exact(self):
        cfg = FleetConfig()
        ref = FleetPool(cfg, dp=2)
        pool = FleetPool(
            cfg, dp=2,
            chaos={0: ChaosInjector(
                events=[ChaosEvent(tick=3, kind=KILL)])})
        prompts = [[i + 2, i + 3, i + 4] for i in range(6)]
        want, got = {}, {}
        for p in prompts:
            want[tuple(p)] = ref.submit(p, 6)
            got[tuple(p)] = pool.submit(p, 6)
        ref_out = {r.rid: r for r in ref.drain()}
        out = {r.rid: r for r in pool.drain()}
        assert pool.failovers >= 1
        assert len(out) == len(prompts)          # exactly once
        for p in prompts:
            assert (out[got[tuple(p)]].tokens
                    == ref_out[want[tuple(p)]].tokens)

    def test_dead_replica_gauge_deleted_after_harvest(self):
        reg = MetricsRegistry()
        pool = FleetPool(
            FleetConfig(), dp=2, metrics=reg,
            chaos={1: ChaosInjector(
                events=[ChaosEvent(tick=1, kind=KILL)])})
        for i in range(4):
            pool.submit([i + 2, i + 3], 4)
        pool.drain()
        assert 1 in pool.dead_replicas
        gauges = reg.snapshot()["gauges"]
        assert "serve_replica_queue_depth_r1" not in gauges
        assert "serve_replica_queue_depth_r0" in gauges

    def test_dead_replica_series_ends_at_harvest_choke_point(self):
        # ISSUE 20 satellite: a SeriesStore attached to the REAL pool
        # registry must close the dead replica's depth series at the
        # same choke point that deletes its gauge — the survivor's
        # series keeps taking points, the dead one stays frozen even
        # though the harvest loop re-deletes the gauge every tick
        from kubegpu_tpu.obs.tsdb import SeriesStore
        reg = MetricsRegistry()
        store = SeriesStore(reg)
        pool = FleetPool(
            FleetConfig(), dp=2, metrics=reg,
            chaos={1: ChaosInjector(
                events=[ChaosEvent(tick=1, kind=KILL)])})
        for i in range(4):
            pool.submit([i + 2, i + 3], 4)
        tick = 0
        while pool._entries or pool._pending_deaths:
            pool.step()
            store.sample(tick)
            tick += 1
        assert 1 in pool.dead_replicas
        assert store.ended("serve_replica_queue_depth_r1")
        assert not store.ended("serve_replica_queue_depth_r0")
        dead = store.series("serve_replica_queue_depth_r1")
        alive = store.series("serve_replica_queue_depth_r0")
        assert alive and alive[-1][0] > (dead[-1][0] if dead else -1)

    def test_disagg_migration_over_sim_engines(self):
        pool = FleetDisaggPool(FleetConfig(), prefill=1, decode=1)
        ref = FleetPool(FleetConfig(), dp=1)
        rids, refs = [], []
        for i in range(4):
            p = [i + 2, i + 3, i + 4, i + 5]
            rids.append(pool.submit(p, 8))
            refs.append(ref.submit(p, 8))
        out = {r.rid: r for r in pool.drain()}
        ref_out = {r.rid: r for r in ref.drain()}
        assert pool.migrations >= 1
        assert len(out) == 4
        for rid, rref in zip(rids, refs):
            assert out[rid].tokens == ref_out[rref].tokens


# -- correlated failure domains ----------------------------------------

class TestDomainChaos:
    def test_injector_validates_scope(self):
        with pytest.raises(ValueError):
            DomainChaosInjector(events=[DomainChaosEvent(
                tick=1, kind=KILL)])          # engine-scope kind
        with pytest.raises(ValueError):
            DomainChaosInjector(events=[DomainChaosEvent(
                tick=1, kind=DOMAIN_KILL)])   # domain without target

    def test_from_seed_deterministic(self):
        a = DomainChaosInjector.from_seed(7, 50, ("rack0", "rack1"),
                                          n_events=4)
        b = DomainChaosInjector.from_seed(7, 50, ("rack0", "rack1"),
                                          n_events=4)
        assert a.events == b.events

    def test_quarter_fleet_dies_in_one_tick_outcomes_identical(self):
        trace = mk_trace()
        twin = run_fleet(trace, TIERS, replicas=64, domains=4)
        chaos = DomainChaosInjector(events=[DomainChaosEvent(
            tick=12, kind=DOMAIN_KILL, domain="rack1")])
        rep = run_fleet(trace, TIERS, replicas=64, domains=4,
                        chaos=chaos)
        assert rep.killed_replicas == 16          # >= 25% in one tick
        assert rep.failovers >= 16
        assert rep.load.lost == 0 and rep.load.duplicated == 0
        assert rep.tier_inversions == 0
        assert compare_outcomes(twin.load, rep.load)["identical"]

    def test_watch_weather_dup_delay_reorder_idempotent(self):
        trace = mk_trace()
        twin = run_fleet(trace, TIERS, replicas=16, domains=4)
        chaos = DomainChaosInjector(events=[
            DomainChaosEvent(tick=8, kind=WATCH_DUP, dup=3,
                             duration_ticks=8),
            DomainChaosEvent(tick=8, kind=WATCH_DELAY,
                             delay_ticks=3, duration_ticks=8),
            DomainChaosEvent(tick=8, kind=WATCH_REORDER,
                             duration_ticks=8),
            DomainChaosEvent(tick=10, kind=DOMAIN_KILL,
                             domain="rack2"),
        ])
        rep = run_fleet(trace, TIERS, replicas=16, domains=4,
                        chaos=chaos)
        # 4 gangs x dup 3 — every duplicate/late delivery a no-op
        assert rep.watch_delivered >= 12
        assert rep.load.lost == 0 and rep.load.duplicated == 0
        assert compare_outcomes(twin.load, rep.load)["identical"]

    def test_watch_partition_stale_reads_then_heal(self):
        trace = mk_trace()
        twin = run_fleet(trace, TIERS, replicas=16, domains=4)
        # evict-only domain loss: the ONLY signal travels the watch,
        # and the watch is partitioned — routing keeps targeting the
        # condemned replicas (stale reads) until heal
        chaos = DomainChaosInjector(events=[
            DomainChaosEvent(tick=9, kind=WATCH_PARTITION,
                             duration_ticks=6),
            DomainChaosEvent(tick=10, kind=DOMAIN_EVICT,
                             domain="rack3"),
        ])
        rep = run_fleet(trace, TIERS, replicas=16, domains=4,
                        chaos=chaos)
        assert rep.domain_evictions == 1
        assert rep.watch_delivered >= 4   # flushed after heal
        assert rep.load.lost == 0 and rep.load.duplicated == 0
        assert compare_outcomes(twin.load, rep.load)["identical"]

    def test_deterministic_by_seed(self):
        trace = mk_trace()

        def once():
            return run_fleet(
                trace, TIERS, replicas=32, domains=4,
                chaos=DomainChaosInjector(events=[DomainChaosEvent(
                    tick=12, kind=DOMAIN_KILL, domain="rack0")]))

        a, b = once(), once()
        cmp_ = compare_outcomes(a.load, b.load)
        assert cmp_["identical"] and cmp_["checked"] == len(trace)


# -- rolling upgrades ---------------------------------------------------

class TestRollingUpgrade:
    def test_waves_cover_all_domains_floor_held(self):
        trace = mk_trace()
        twin = run_fleet(trace, TIERS, replicas=64, domains=4)
        # floor HALF a domain above worst case: the first drain batch
        # lands exactly on the floor, so completion proves the
        # controller backfills mid-wave instead of wedging
        rep = run_fleet(trace, TIERS, replicas=64, domains=4,
                        upgrade=True, upgrade_floor=56,
                        upgrade_surge=4, upgrade_start=8)
        assert rep.upgrade_waves == 4             # every domain
        assert rep.upgraded_replicas == 64        # whole fleet
        assert rep.min_alive >= 56                # floor never broken
        assert rep.load.lost == 0 and rep.load.duplicated == 0
        assert rep.tier_inversions == 0
        assert compare_outcomes(twin.load, rep.load)["identical"]

    def test_surge_credit_returns_fleet_to_nominal(self):
        pool = FleetPool(FleetConfig(), dp=8, max_replicas=24)
        topo = FleetTopology.grid(8, 2)
        for i in range(8):
            pool.bind_replica_gang(i, f"g{i}")
        upg = UpgradeWaveController(pool, topo, floor=6, surge=2)
        tick = 0
        while not upg.done and tick < 200:
            upg.on_tick(tick)
            pool.step()
            tick += 1
        assert upg.done and upg.waves_done == 2
        assert len(pool._alive()) == 8            # nominal size
        assert upg.min_alive >= 6


# -- control-plane crash recovery ---------------------------------------

class TestCrashRecovery:
    def test_mid_trace_crash_recovers_exactly_once(self):
        trace = mk_trace()
        twin = run_fleet(trace, TIERS, replicas=32, domains=4)
        journal = ControlPlaneJournal()
        rep = run_fleet(trace, TIERS, replicas=32, domains=4,
                        journal=journal, crash_at=12)
        assert rep.recoveries == 1
        assert rep.redriven >= 1                  # genuinely mid-trace
        assert rep.load.lost == 0 and rep.load.duplicated == 0
        assert rep.tier_inversions == 0
        assert compare_outcomes(twin.load, rep.load)["identical"]
        c = journal.counts()
        assert c["crash"] == 1 and c["recovered"] == 1
        assert c["finish"] >= c["submit"]         # every gid settled
        assert c["resubmit"] == rep.redriven

    def test_journal_inflight_is_submits_minus_finishes(self):
        j = ControlPlaneJournal()
        j.append("submit", gid=0, tier=0)
        j.append("submit", gid=1, tier=1)
        j.append("finish", gid=0)
        assert j.inflight() == [1]

    def test_recovery_redrives_in_tier_order(self):
        trace = mk_trace()
        journal = ControlPlaneJournal()
        run_fleet(trace, TIERS, replicas=32, domains=4,
                  journal=journal, crash_at=12)
        redriven = [r["tier"] for r in journal.records
                    if r["kind"] == "resubmit"]
        assert redriven == sorted(redriven)


# -- loadgen extensions -------------------------------------------------

class TestLoadgenFleetKnobs:
    def test_default_knobs_leave_traces_bit_identical(self):
        base = dict(seed=11, n_requests=24, tiers=TIERS)
        a = synth_trace(LoadSpec(**base))
        b = synth_trace(LoadSpec(**base, diurnal=False, flash_at=()))
        assert len(a) == len(b)
        for x, y in zip(a, b):
            assert x["arrival_tick"] == y["arrival_tick"]
            assert np.array_equal(x["prompt"], y["prompt"])

    def test_diurnal_and_flash_modulate_arrivals(self):
        base = dict(seed=11, n_requests=48, tiers=TIERS)
        plain = synth_trace(LoadSpec(**base))
        diurnal = synth_trace(LoadSpec(**base, diurnal=True))
        flash = synth_trace(LoadSpec(**base, flash_at=(2.0,),
                                     flash_rate_x=8.0,
                                     flash_len_ticks=10.0))
        t = [e["arrival_tick"] for e in plain]
        assert [e["arrival_tick"] for e in diurnal] != t
        tf = [e["arrival_tick"] for e in flash]
        assert tf != t and tf[-1] < t[-1]         # compressed burst


# -- chip-tick cost attribution (ISSUE 20) ------------------------------

class TestChipTickAttribution:
    def test_conservation_exact_under_domain_kill(self):
        trace = synth_trace(LoadSpec(
            seed=1907, n_requests=96, mean_iat_ticks=0.25, tiers=TIERS,
            tenants=("acme", "blue", "coral"), diurnal=True))
        chaos = DomainChaosInjector(events=[DomainChaosEvent(
            tick=10, kind=DOMAIN_KILL, domain="rack1")])
        rep = run_fleet(trace, TIERS, replicas=16, domains=4,
                        chaos=chaos)
        assert rep.busy_chip_ticks > 0
        # exact integer conservation: every busy replica-tick lands on
        # exactly one (tenant, tier) key, dead replicas included
        assert sum(rep.cost_by_key.values()) == rep.busy_chip_ticks
        assert rep.busy_chip_ticks == rep.busy_ticks   # sim tp=1
        assert all(isinstance(v, int) for v in rep.cost_by_key.values())
        # every tenant that ran shows up billed
        tenants = {k.split(":")[0] for k in rep.cost_by_key}
        assert tenants == {"acme", "blue", "coral"}

    def test_crash_closure_keeps_pre_crash_charges(self):
        trace = mk_trace(n=64)
        rep = run_fleet(trace, TIERS, replicas=16, domains=4,
                        journal=ControlPlaneJournal(), crash_at=12)
        assert rep.recoveries == 1
        # the pre-crash pool's ledger was merged, not dropped: the
        # total still balances against total busy ticks
        assert sum(rep.cost_by_key.values()) == rep.busy_chip_ticks
        assert rep.busy_chip_ticks == rep.busy_ticks

    def test_cost_summary_joins_goodput(self):
        trace = synth_trace(LoadSpec(
            seed=7, n_requests=48, mean_iat_ticks=0.25, tiers=TIERS,
            tenants=("acme", "blue")))
        rep = run_fleet(trace, TIERS, replicas=8, domains=4)
        cs = rep.cost_summary()
        assert cs["busy_chip_ticks"] == rep.busy_chip_ticks
        assert cs["attributed_chip_ticks"] == rep.busy_chip_ticks
        for key, row in cs["per_key"].items():
            assert row["chip_ticks"] >= 0
            assert row["goodput_tokens"] <= row["total_tokens"]
            if row["chip_ticks"]:
                assert row["goodput_per_chip_tick"] == pytest.approx(
                    row["goodput_tokens"] / row["chip_ticks"], rel=1e-3)
        assert cs["goodput_per_chip_tick"] > 0

    def test_suffixed_gauges_published(self):
        reg = MetricsRegistry()
        trace = synth_trace(LoadSpec(
            seed=7, n_requests=32, mean_iat_ticks=0.25, tiers=TIERS,
            tenants=("acme",)))
        rep = run_fleet(trace, TIERS, replicas=8, domains=4,
                        metrics=reg)
        g = reg.snapshot()["gauges"]
        assert g["serve_chip_ticks_total"] == float(rep.busy_chip_ticks)
        per = {k: v for k, v in g.items()
               if k.startswith("serve_chip_ticks_total_")}
        assert per, "per-key suffixed gauges missing"
        assert sum(per.values()) == float(rep.busy_chip_ticks)

    def test_attribution_is_deterministic(self):
        trace = mk_trace(n=48)
        a = run_fleet(trace, TIERS, replicas=8, domains=4)
        b = run_fleet(trace, TIERS, replicas=8, domains=4)
        assert a.cost_by_key == b.cost_by_key
        assert a.busy_chip_ticks == b.busy_chip_ticks

"""Parity + sanitizer tests for the C++ allocator core.

SURVEY.md §8 step 3: the hot loop gets a C++ port, property-tested hard —
random meshes × random occupancy × random shapes must produce *identical*
results from the native core and the pure-Python reference implementations
(which stay in-tree as the spec).  §6: the core also builds and runs under
-fsanitize=address,undefined.
"""

from __future__ import annotations

import os
import random
import subprocess
from pathlib import Path

import pytest

from kubegpu_tpu.allocator import _native
from kubegpu_tpu.allocator.ordering import candidate_orders
from kubegpu_tpu.topology.locality import (
    ici_locality,
    traffic_pairs_for_mesh_axes,
)
from kubegpu_tpu.topology.mesh import TOPOLOGY_REGISTRY, TpuTopology
from kubegpu_tpu.topology.slices import (
    Placement,
    enumerate_placements,
    find_free_placements,
    fragmentation_score,
    subslice_shapes,
)

pytestmark = pytest.mark.skipif(
    not _native.available(), reason="native core unavailable (no g++?)")


class _python_path:
    """Run the real production functions with the native core disabled —
    the Python implementations in-tree ARE the spec the core must match."""

    def __enter__(self):
        os.environ["KUBETPU_NO_NATIVE"] = "1"

    def __exit__(self, *exc):
        os.environ.pop("KUBETPU_NO_NATIVE", None)


def _py_find_free(topo, occupied, shape, limit):
    with _python_path():
        return find_free_placements(topo, occupied, shape, limit)


def _py_frag(topo, occupied, placement):
    with _python_path():
        return fragmentation_score(topo, occupied, placement)


def _random_axes(rng, n):
    """Random ordered factorization of n into 1–3 named axes."""
    names = ["dp", "fsdp", "tp"]
    sizes = []
    rest = n
    for _ in range(rng.randrange(1, 3)):
        divs = [d for d in range(2, rest + 1) if rest % d == 0]
        if not divs:
            break
        d = rng.choice(divs)
        sizes.append(d)
        rest //= d
    sizes.append(rest)
    return {names[i]: s for i, s in enumerate(sizes)}


@pytest.mark.parametrize("topo_name", sorted(TOPOLOGY_REGISTRY))
def test_find_free_placements_parity(topo_name):
    topo = TpuTopology.build(TOPOLOGY_REGISTRY[topo_name])
    rng = random.Random(hash(topo_name) & 0xFFFF)
    all_coords = [ch.coord for ch in topo.chips]
    n = topo.spec.num_chips
    for trial in range(30):
        occupied = set(rng.sample(all_coords, rng.randrange(0, n)))
        total = rng.choice([1, 2, 4, 8, 16, 32])
        if total > n:
            continue
        for shape in subslice_shapes(total, topo.spec.mesh_shape):
            for limit in (None, 3):
                py = _py_find_free(topo, occupied, shape, limit)
                nat = _native.find_free_placements_native(
                    topo, occupied, shape, limit)
                assert nat is not None
                assert [p.origin for p in nat] == [p.origin for p in py]
                assert [p.coords for p in nat] == [p.coords for p in py]


@pytest.mark.parametrize("topo_name", ["v5e-16", "v5e-64", "v5e-256",
                                       "v4-16"])
def test_rank_free_placements_parity(topo_name):
    """The fused C enumerate+frag-rank must return exactly what the
    Python pipeline (find placements → frag each → stable sort desc →
    truncate) returns — origins, coords, and scores."""
    from kubegpu_tpu.topology.slices import (
        find_free_placements,
        fragmentation_score,
    )

    topo = TpuTopology.build(TOPOLOGY_REGISTRY[topo_name])
    rng = random.Random(hash(topo_name) & 0xFFF)
    all_coords = [ch.coord for ch in topo.chips]
    n = topo.spec.num_chips
    for _ in range(15):
        occupied = set(rng.sample(all_coords, rng.randrange(0, n)))
        total = rng.choice([2, 4, 8, 16])
        if total > n:
            continue
        for shape in subslice_shapes(total, topo.spec.mesh_shape):
            for limit, k in ((None, 4), (6, 2), (64, 8)):
                nat = _native.rank_free_placements_native(
                    topo, occupied, shape, limit, k)
                assert nat is not None
                pls = find_free_placements(topo, occupied, shape,
                                           limit=limit)
                ranked = [(fragmentation_score(topo, occupied, pl), pl)
                          for pl in pls]
                ranked.sort(key=lambda t: -t[0])   # stable: ties keep
                want = ranked[:k]                  # enumeration order
                assert len(nat) == len(want)
                for (nf, npl), (wf, wpl) in zip(nat, want):
                    assert nf == pytest.approx(wf, abs=1e-12)
                    assert npl.origin == wpl.origin
                    assert npl.coords == wpl.coords


@pytest.mark.parametrize("topo_name", ["v5e-16", "v5e-64", "v5e-256",
                                       "v4-16", "v5p-128"])
def test_eval_order_parity(topo_name):
    topo = TpuTopology.build(TOPOLOGY_REGISTRY[topo_name])
    rng = random.Random(42)
    for total in (4, 8, 16):
        if total > topo.spec.num_chips:
            continue
        for shape in subslice_shapes(total, topo.spec.mesh_shape)[:3]:
            pls = enumerate_placements(topo, shape)[:4]
            for pl in pls:
                for order in candidate_orders(pl)[:6]:
                    axes = _random_axes(rng, total)
                    weights = {k: rng.choice([1.0, 2.0, 8.0])
                               for k in axes}
                    py = ici_locality(
                        topo,
                        traffic_pairs_for_mesh_axes(order, axes, weights))
                    nat = _native.eval_order_native(
                        topo, order, axes, weights)
                    assert nat is not None
                    assert nat == pytest.approx(py, abs=1e-12), (
                        topo_name, shape, axes, weights)


@pytest.mark.parametrize("topo_name", ["v5e-64", "v5e-256", "v5p-128"])
def test_fragmentation_parity(topo_name):
    topo = TpuTopology.build(TOPOLOGY_REGISTRY[topo_name])
    rng = random.Random(7)
    all_coords = [ch.coord for ch in topo.chips]
    for _ in range(20):
        occupied = set(rng.sample(all_coords,
                                  rng.randrange(0, len(all_coords) // 2)))
        total = rng.choice([4, 8, 16])
        shape = rng.choice(subslice_shapes(total, topo.spec.mesh_shape))
        pls = _py_find_free(topo, occupied, shape, 5)
        for pl in pls:
            py = _py_frag(topo, occupied, pl)
            nat = _native.fragmentation_score_native(
                topo, occupied, pl.coords)
            assert nat == pytest.approx(py, abs=1e-12)


@pytest.mark.parametrize("topo_name", ["v5e-16", "v5e-64", "v5e-256"])
def test_orient_rings_parity(topo_name, monkeypatch):
    """_orient_rings (the measured hot loop) picks identical orientations
    native vs python across placements of many shapes."""
    from kubegpu_tpu.allocator import gang as gang_mod

    topo = TpuTopology.build(TOPOLOGY_REGISTRY[topo_name])
    for total in (8, 16, 32, 64):
        if total > topo.spec.num_chips:
            continue
        for shape in subslice_shapes(total, topo.spec.mesh_shape)[:4]:
            for pl in enumerate_placements(topo, shape)[:3]:
                for span in (None, 16):
                    monkeypatch.setenv("KUBETPU_NO_NATIVE", "1")
                    py = gang_mod._block_orders(topo, pl, span)
                    monkeypatch.delenv("KUBETPU_NO_NATIVE")
                    nat = gang_mod._block_orders(topo, pl, span)
                    assert nat == py, (topo_name, shape, pl.origin, span)


def test_connected_set_fragmentation():
    """Degenerate (non-rectangular) placements also go through native."""
    topo = TpuTopology.build(TOPOLOGY_REGISTRY["v5e-16"])
    coords = ((0, 0, 0), (0, 1, 0), (1, 0, 0))
    pl = Placement(origin=(0, 0, 0), shape=(0, 0, 0), coords=coords)
    occupied = {(1, 1, 0), (2, 0, 0)}
    assert _native.fragmentation_score_native(
        topo, occupied, pl.coords) == pytest.approx(
        _py_frag(topo, occupied, pl), abs=1e-12)


def test_allocator_end_to_end_native_vs_python(monkeypatch):
    """Full GangAllocator decisions are identical with the core on/off."""
    from kubegpu_tpu.allocator import _native as nat_mod
    from kubegpu_tpu.allocator.gang import GangAllocator, GangRequest
    from kubegpu_tpu.allocator.gang import SliceState
    from kubegpu_tpu.tpuplugin.mock import MockBackend

    def build_slices():
        spec = MockBackend("v5e-64", slice_id="s0").spec
        advs = [MockBackend("v5e-64", host_id=h, slice_id="s0").discover()
                for h in range(spec.num_hosts)]
        return [SliceState.from_advertisements(advs)]

    reqs = [
        GangRequest("g0", num_pods=4, chips_per_pod=4,
                    mesh_axes={"dp": 4, "tp": 4}),
        GangRequest("g1", num_pods=8, chips_per_pod=4,
                    mesh_axes={"dp": 2, "tp": 16},
                    axis_weights={"dp": 1.0, "tp": 8.0}),
        GangRequest("g2", num_pods=1, chips_per_pod=2),
        GangRequest("g3", num_pods=1, chips_per_pod=3),  # connected-set path
        GangRequest("g4", num_pods=2, chips_per_pod=3),  # may be infeasible
    ]

    def run(native: bool):
        if not native:
            monkeypatch.setenv("KUBETPU_NO_NATIVE", "1")
        else:
            monkeypatch.delenv("KUBETPU_NO_NATIVE", raising=False)
        slices = build_slices()
        alloc = GangAllocator()
        out = []
        for r in reqs:
            a = alloc.find_assignment(slices, r)
            if a is None:
                out.append(None)
                continue
            alloc.commit({s.slice_id: s for s in slices}, a)
            out.append((a.slice_id, a.locality, a.score,
                        [(p.pod_index, p.host_id,
                          tuple(c.coord for c in p.chips))
                         for p in a.pods]))
        return out

    native_out = run(True)
    python_out = run(False)
    assert native_out == python_out


def test_asan_build_and_run():
    """Build and run the address+UB-sanitized driver over every exported
    entry point (SURVEY.md §6 race/sanitizer row)."""
    csrc = Path(_native.__file__).parent / "csrc"
    try:
        subprocess.run(["make", "-s", "asan"], cwd=csrc, check=True,
                       capture_output=True, timeout=180)
    except (OSError, subprocess.SubprocessError) as e:
        pytest.skip(f"asan build unavailable: {e}")
    res = subprocess.run(
        [str(csrc / "sanitize_check")], capture_output=True, text=True,
        timeout=120, env={"PATH": "/usr/bin:/bin",
                          "ASAN_OPTIONS": "detect_leaks=0"})
    assert res.returncode == 0, res.stdout + res.stderr
    assert "sanitize OK" in res.stdout


def test_align_units_parity():
    """ktpu_align_units picks the identical orientation sequence as the
    Python Viterbi (tie-breaking included) on randomized ring sets."""
    import random

    from kubegpu_tpu.allocator import gang as gang_mod

    rng = random.Random(7)
    for trial in range(120):
        n_units = rng.randint(2, 6)
        ring_len = rng.choice([2, 4, 8])
        step = rng.choice([1, 2])
        units = []
        for _ in range(n_units):
            base = rng.randint(0, 5)
            units.append([(base + i % 3, (base + i) % 4, rng.randint(0, 2))
                          for i in range(ring_len)])
        options = [gang_mod._cycle_variants(u, step) for u in units]
        nat = _native.align_units_native(options)
        if nat is None:
            pytest.skip("native core unavailable")
        # python reference (bypass the native dispatch inside _align_units)
        import os
        os.environ["KUBETPU_NO_NATIVE"] = "1"
        try:
            py = gang_mod._align_units(units, step)
        finally:
            del os.environ["KUBETPU_NO_NATIVE"]
        assert nat == py, (trial, units)


def test_connected_order_parity(monkeypatch):
    """Native connected-region fallback returns the same chunked order as
    the Python BFS, across random occupancy and gang shapes."""
    import random

    from kubegpu_tpu.allocator.gang import (
        GangAllocator, GangRequest, SliceState,
    )
    from kubegpu_tpu.tpuplugin.mock import MockBackend

    rng = random.Random(11)
    for trial in range(40):
        slice_type = rng.choice(["v4-8", "v5e-16", "v5e-64"])
        spec = MockBackend(slice_type, slice_id="s0").spec
        advs = [MockBackend(slice_type, host_id=h, slice_id="s0").discover()
                for h in range(spec.num_hosts)]

        def build():
            st = SliceState.from_advertisements(advs)
            # fragment the slice randomly (same picks per build)
            frag_rng = random.Random(trial)
            for ch in st.topo.chips:
                if frag_rng.random() < 0.35:
                    st.used_millichips[ch.coord] = 1000
            return st

        pods = rng.choice([1, 2, 3])
        cpp = rng.choice([1, 2, 3])
        req = GangRequest("g", num_pods=pods, chips_per_pod=cpp)
        alloc = GangAllocator()
        st_n = build()
        blocked_n = st_n.blocked_for_whole()
        axes = {"dp": pods * cpp}
        nat = alloc._connected_candidate(st_n, req, blocked_n, axes)
        monkeypatch.setenv("KUBETPU_NO_NATIVE", "1")
        try:
            st_p = build()
            py = alloc._connected_candidate(st_p, req,
                                            st_p.blocked_for_whole(), axes)
        finally:
            monkeypatch.delenv("KUBETPU_NO_NATIVE")
        if py is None:
            assert nat is None, trial
        else:
            assert nat is not None, trial
            assert nat.order == py.order, trial
            assert nat.score == pytest.approx(py.score), trial

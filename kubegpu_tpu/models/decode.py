"""Autoregressive serving path for the Llama family: KV-cache prefill +
single-token decode, both jit-compiled with static shapes.

The reference schedules training jobs and has no serving stack; this is
the TPU-native inference complement to :mod:`kubegpu_tpu.models.llama`
(same stacked-layer params, same rope/rmsnorm/GQA math), built the way
XLA wants a decode loop:

- the cache is a stacked ``[L, B, Hkv, max_len, hd]`` pair preallocated
  once — decode writes slot ``pos`` with ``dynamic_update_slice`` and
  never reshapes, so every step hits the same compiled executable;
- attention always spans the full ``max_len`` with an explicit
  ``k_pos <= q_pos`` mask (unwritten slots mask out) — static shapes, no
  data-dependent slicing under jit;
- generation is one ``lax.scan`` over steps (greedy argmax feedback), so
  an N-token generation is a single XLA program, not N dispatches;
- tensor-parallel serving falls out of GSPMD: the same einsums shard on
  ``tp`` when params carry :func:`llama_param_specs` shardings.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from kubegpu_tpu.models.llama import LlamaConfig, _rmsnorm, _rope
from kubegpu_tpu.ops.flash_attention import NEG_INF
from kubegpu_tpu.ops.kvquant import quantize_rows


def init_kv_cache(cfg: LlamaConfig, batch: int,
                  max_len: int | None = None,
                  kv_int8: bool = False) -> dict:
    """Zeroed stacked cache; ``max_len`` defaults to cfg.max_seq_len.

    ``kv_int8`` stores K/V as int8 with per-(layer, batch, head, token)
    f32 scales: at wide serving batches the cache out-reads even int8
    weights, so halving cache bytes is the next decode lever.  Scales
    init to 1 so unwritten slots dequantize to exact zero."""
    s = max_len or cfg.max_seq_len
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, s, cfg.head_dim)
    if not kv_int8:
        return {"k": jnp.zeros(shape, cfg.jdtype),
                "v": jnp.zeros(shape, cfg.jdtype)}
    sshape = shape[:-1]
    return {"k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.ones(sshape, jnp.float32),
            "v_scale": jnp.ones(sshape, jnp.float32)}


# Quantizer math lives in the shared ops module (ISSUE 15 satellite:
# the dense int8 cache, the paged int8 pool, and the packed int4 pool
# all rate through ONE implementation); the underscore alias keeps the
# historical import path every pool write site uses.
_quantize_rows = quantize_rows


def _cached_attend(q: jax.Array, ck: jax.Array, cv: jax.Array,
                   q_pos: jax.Array) -> jax.Array:
    """q: [B, Hq, T, D]; cache k/v: [B, Hkv, S, D]; q_pos: [T] global
    positions.  Masks ``k_pos > q_pos`` — causality and the unwritten
    tail of the cache in one predicate.

    GQA runs grouped, NOT via repeat_kv: decode is cache-read bound,
    and materializing Hq/Hkv head-repeated (and f32-upcast) copies of
    the whole cache per step multiplied the HBM traffic by up to 8x —
    measured 7x slower at batch 32.  The grouped einsum reads each
    cache element once, in its stored dtype, with f32 accumulation."""
    b, hq, t, d = q.shape
    hkv, s = ck.shape[1], ck.shape[2]
    qg = q.reshape(b, hkv, hq // hkv, t, d)
    scale = d ** -0.5
    scores = jnp.einsum("bkgtd,bksd->bkgts", qg, ck,
                        preferred_element_type=jnp.float32) * scale
    k_pos = jnp.arange(s)
    scores = jnp.where(
        (k_pos[None, :] <= q_pos[:, None])[None, None, None],
        scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgts,bksd->bkgtd", probs, cv,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, hq, t, d).astype(q.dtype)


def _cached_attend_q8(q: jax.Array, ck: jax.Array, cv: jax.Array,
                      k_scale: jax.Array, v_scale: jax.Array,
                      q_pos: jax.Array) -> jax.Array:
    """int8-cache variant of :func:`_cached_attend`: cache values are
    int8 [B, Hkv, S, D] with f32 per-token scales [B, Hkv, S].  The
    scales fold into the score matrix (k) and the probability matrix
    (v) — the cache itself streams from HBM as int8, which is the whole
    point; no dequantized copy is ever materialized."""
    b, hq, t, d = q.shape
    hkv, s = ck.shape[1], ck.shape[2]
    qg = q.reshape(b, hkv, hq // hkv, t, d)
    scale = d ** -0.5
    scores = jnp.einsum("bkgtd,bksd->bkgts", qg,
                        ck.astype(q.dtype),
                        preferred_element_type=jnp.float32)
    scores = scores * (scale * k_scale[:, :, None, None, :])
    k_pos = jnp.arange(s)
    scores = jnp.where(
        (k_pos[None, :] <= q_pos[:, None])[None, None, None],
        scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgts,bksd->bkgtd",
                     probs * v_scale[:, :, None, None, :],
                     cv.astype(q.dtype),
                     preferred_element_type=jnp.float32)
    return out.reshape(b, hq, t, d).astype(q.dtype)


def _dense_ffn(x: jax.Array, lp: dict, cfg: LlamaConfig,
               tp_axis: str | None = None) -> jax.Array:
    """The Llama SwiGLU FFN sublayer (residual included) — the default
    ``ffn`` of the cached forward; the MoE family swaps in its routed
    experts here (models/moe.py serving section).  Under a shard_map'd
    tensor-parallel step (``tp_axis``) the gate/up weights are
    column-sharded on d_ff and the down projection is row-sharded, so
    the local product is a partial sum psum'd over the axis (the
    megatron mlp allreduce)."""
    h = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
    up = jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])
    down = up @ lp["w_down"]
    if tp_axis is not None:
        down = lax.psum(down, tp_axis)
    return x + down.astype(x.dtype)


def _project_qkv(h: jax.Array, lp: dict, cfg: LlamaConfig,
                 positions: jax.Array
                 ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Normed input [B, T, D] → rope'd (q, k, v) as [B, H, T, hd].
    THE qkv block of every decode-path forward (_forward_with_cache,
    the serve engine's per-row step, the beam two-segment step) —
    bit-parity between those paths and greedy decode depends on this
    math existing exactly once."""
    b, t = h.shape[0], h.shape[1]
    hd = cfg.head_dim
    q = (h @ lp["wq"]).reshape(b, t, cfg.n_heads, hd)
    k = (h @ lp["wk"]).reshape(b, t, cfg.n_kv_heads, hd)
    v = (h @ lp["wv"]).reshape(b, t, cfg.n_kv_heads, hd)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    return (q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3))


def _attn_finish(x: jax.Array, o: jax.Array, lp: dict,
                 cfg: LlamaConfig, ffn,
                 tp_axis: str | None = None) -> jax.Array:
    """Attention output [B, H, T, hd] → wo projection + residual +
    feed-forward — the back half shared by the same three paths.
    Under tensor parallelism (``tp_axis``, inside shard_map) ``o``
    holds only this chip's heads and ``wo`` the matching rows, so the
    projection is a partial sum psum'd over the axis (the megatron
    attention allreduce); ``cfg`` is then the LOCAL per-chip config."""
    b, t = x.shape[0], x.shape[1]
    o = o.transpose(0, 2, 1, 3).reshape(b, t, cfg.n_heads * cfg.head_dim)
    proj = o @ lp["wo"]
    if tp_axis is not None:
        proj = lax.psum(proj, tp_axis)
    x = x + proj.astype(x.dtype)
    return ffn(x, lp)


def _forward_with_cache(params: dict, tokens: jax.Array, cache: dict,
                        pos_offset: jax.Array, cfg: LlamaConfig,
                        ffn=None, tp_axis: str | None = None
                        ) -> tuple[jax.Array, dict]:
    """Run the decoder over ``tokens`` [B, T] starting at global position
    ``pos_offset`` (scalar), reading + writing the cache.  Returns
    (logits [B, T, vocab] f32, updated cache).  T=prompt for prefill,
    T=1 for decode — same code path, same executable shape per T.
    ``ffn(x, lp) -> x`` overrides the feed-forward sublayer (MoE).

    ``tp_axis`` (inside a shard_map over that mesh axis): ``cfg`` is the
    LOCAL config (n_heads/n_kv_heads/d_ff divided by the axis size),
    the cache holds local KV heads, per-layer partial projections psum
    over the axis, and the returned logits are the LOCAL vocab shard
    [B, T, V/tp] — the caller all-gathers after position selection."""
    b, t = tokens.shape
    if ffn is None:
        ffn = lambda x, lp: _dense_ffn(x, lp, cfg,   # noqa: E731
                                       tp_axis=tp_axis)
    kv_int8 = "k_scale" in cache
    x = jnp.take(params["embed"], tokens, axis=0)
    q_pos = pos_offset + jnp.arange(t)
    positions = jnp.broadcast_to(q_pos[None, :], (b, t))

    def project_kv(h, lp):
        return _project_qkv(h, lp, cfg, positions)

    def finish(x, o, lp):
        return _attn_finish(x, o, lp, cfg, ffn, tp_axis=tp_axis)

    if kv_int8:
        def layer(x, xs):
            lp, ck, cv, ks, vs = xs
            h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
            q, k, v = project_kv(h, lp)
            kq, kscale = _quantize_rows(k)
            vq, vscale = _quantize_rows(v)
            ck = lax.dynamic_update_slice(ck, kq, (0, 0, pos_offset, 0))
            cv = lax.dynamic_update_slice(cv, vq, (0, 0, pos_offset, 0))
            ks = lax.dynamic_update_slice(ks, kscale, (0, 0, pos_offset))
            vs = lax.dynamic_update_slice(vs, vscale, (0, 0, pos_offset))
            o = _cached_attend_q8(q, ck, cv, ks, vs, q_pos)
            return finish(x, o, lp), (ck, cv, ks, vs)

        x, (ck_new, cv_new, ks_new, vs_new) = lax.scan(
            layer, x,
            (params["layers"], cache["k"], cache["v"],
             cache["k_scale"], cache["v_scale"]))
        new_cache = {"k": ck_new, "v": cv_new,
                     "k_scale": ks_new, "v_scale": vs_new}
    else:
        def layer(x, xs):
            lp, ck, cv = xs
            h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
            q, k, v = project_kv(h, lp)
            # write the new K/V rows at pos_offset ([B, Hkv, S, D])
            ck = lax.dynamic_update_slice(
                ck, k.astype(ck.dtype), (0, 0, pos_offset, 0))
            cv = lax.dynamic_update_slice(
                cv, v.astype(cv.dtype), (0, 0, pos_offset, 0))
            o = _cached_attend(q, ck, cv, q_pos)
            return finish(x, o, lp), (ck, cv)

        x, (ck_new, cv_new) = lax.scan(
            layer, x, (params["layers"], cache["k"], cache["v"]))
        new_cache = {"k": ck_new, "v": cv_new}
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, new_cache


def prefill(params: dict, prompt: jax.Array, cfg: LlamaConfig,
            max_len: int | None = None,
            kv_int8: bool = False, ffn=None) -> tuple[jax.Array, dict]:
    """Process the whole prompt [B, T]; returns (last-position logits
    [B, vocab], primed cache)."""
    cache = init_kv_cache(cfg, prompt.shape[0], max_len,
                          kv_int8=kv_int8)
    logits, cache = _forward_with_cache(
        params, prompt, cache, jnp.int32(0), cfg, ffn=ffn)
    return logits[:, -1], cache


def decode_step(params: dict, cache: dict, token: jax.Array,
                pos: jax.Array, cfg: LlamaConfig, ffn=None
                ) -> tuple[jax.Array, dict]:
    """One token in, next-token logits out.  token: [B], pos: scalar
    global position of ``token``."""
    logits, cache = _forward_with_cache(
        params, token[:, None], cache, pos, cfg, ffn=ffn)
    return logits[:, 0], cache


@functools.lru_cache(maxsize=64)
def _generate_fn(cfg: LlamaConfig, t: int, n_steps: int, max_len: int,
                 kv_int8: bool = False, ffn_factory=None, ffn_cfg=None):
    """One compiled executable per (config, prompt len, steps, cache len)
    — repeat generations with the same shapes hit XLA's cache instead of
    re-tracing (the jit cache is keyed on the function object, so it must
    be created once per static signature, not per call).

    ``ffn_factory(ffn_cfg)`` (both hashable, so they key the cache)
    builds a feed-forward override for the cached forward — how the MoE
    family reuses this machinery with routed experts."""
    ffn = ffn_factory(ffn_cfg) if ffn_factory is not None else None

    @jax.jit
    def run(params, prompt):
        return _rollout(params, prompt, cfg, t, n_steps, max_len,
                        kv_int8,
                        pick=lambda logits, i: jnp.argmax(logits, -1),
                        ffn=ffn)

    return run


def _nucleus_mask(sorted_l: jax.Array, top_p: jax.Array) -> jax.Array:
    """Given DESC-sorted logits, NEG_INF-mask everything outside the
    smallest prefix whose EXCLUSIVE cumulative probability is < top_p
    (always keeps >= 1 token; top_p >= 1 keeps everything)."""
    probs = jax.nn.softmax(sorted_l, axis=-1)
    cum_excl = jnp.cumsum(probs, axis=-1) - probs
    return jnp.where(cum_excl < top_p, sorted_l, NEG_INF)


def _sample_token(logits: jax.Array, key: jax.Array,
                  temperature: jax.Array, top_p: jax.Array,
                  top_k: int, nucleus: bool) -> jax.Array:
    """One sampling step over [B, V] f32 logits — temperature scaling,
    static top-k truncation, dynamic top-p (nucleus) truncation, then a
    categorical draw.  Cost matters in the scanned decode loop: with
    top_k set, the sort (and the nucleus inside it) runs over only k
    elements; with neither truncation (``nucleus=False``, the static
    did-the-caller-pass-top_p<1 flag), no sort happens at all.  A pure
    top_p (top_k=0, nucleus) needs the full-vocab sort — measured ~3x
    the decode step on v5e at V=32k, so serving configs should set
    top_k too."""
    l = logits / jnp.maximum(temperature, 1e-6)
    if top_k:
        vals, idx = lax.top_k(l, top_k)           # [B, k] desc
        if nucleus:   # static: top_p=1.0 callers skip the no-op mask
            vals = _nucleus_mask(vals, top_p)
        choice = jax.random.categorical(key, vals, axis=-1)   # [B]
        return jnp.take_along_axis(idx, choice[:, None], 1)[:, 0]
    if not nucleus:
        return jax.random.categorical(key, l, axis=-1)
    # exact full-vocab nucleus (top_k=0, top_p<1): needs the full sort
    sorted_l, sorted_idx = lax.top_k(l, l.shape[-1])
    masked = _nucleus_mask(sorted_l, top_p)
    choice = jax.random.categorical(key, masked, axis=-1)
    return jnp.take_along_axis(sorted_idx, choice[:, None], 1)[:, 0]


def _validate_rollout(cfg: LlamaConfig, t: int, n_steps: int,
                      max_len: int | None) -> int:
    """Shared length contract for greedy and sampled generation —
    returns the resolved max_len."""
    max_len = max_len or cfg.max_seq_len
    if n_steps < 1:
        raise ValueError(f"n_steps must be >= 1, got {n_steps}")
    if t + n_steps > max_len:
        raise ValueError(
            f"prompt {t} + steps {n_steps} > max_len {max_len}")
    return max_len


def _rollout(params, prompt, cfg: LlamaConfig, t: int, n_steps: int,
             max_len: int, kv_int8: bool, pick, ffn=None):
    """THE decode loop — prefill, then ``n_steps - 1`` scanned decode
    forwards (the prefill already yields the first token's logits, the
    last token needs no successor) — shared by greedy and sampled
    generation so the position bookkeeping and cache threading can
    never diverge between them.  ``pick(logits, step_index)`` is the
    trace-time-static token-selection rule."""
    logits, cache = prefill(params, prompt, cfg, max_len,
                            kv_int8=kv_int8, ffn=ffn)
    first = pick(logits, 0).astype(prompt.dtype)

    def step(carry, i):
        token, cache = carry
        logits, cache = decode_step(params, cache, token, t + i, cfg,
                                    ffn=ffn)
        nxt = pick(logits, i + 1).astype(token.dtype)
        return (nxt, cache), nxt

    (_, _), rest = lax.scan(step, (first, cache),
                            jnp.arange(n_steps - 1))
    toks = jnp.concatenate([first[None], rest], axis=0)
    return toks.swapaxes(0, 1)


@functools.lru_cache(maxsize=64)
def _sample_fn(cfg: LlamaConfig, t: int, n_steps: int, max_len: int,
               top_k: int, nucleus: bool, kv_int8: bool):
    """Compiled sampled-generation executable per static signature
    (temperature/top_p stay dynamic args — no recompile per setting;
    ``nucleus`` is static so top_p=1.0 callers skip the sort)."""

    @jax.jit
    def run(params, prompt, key, temperature, top_p):
        keys = jax.random.split(key, n_steps)

        def pick(logits, i):
            return _sample_token(logits, keys[i], temperature, top_p,
                                 top_k, nucleus)

        return _rollout(params, prompt, cfg, t, n_steps, max_len,
                        kv_int8, pick)

    return run


def sample_generate(params: dict, prompt: jax.Array, n_steps: int,
                    cfg: LlamaConfig, key: jax.Array,
                    temperature: float = 1.0, top_k: int = 0,
                    top_p: float = 1.0, max_len: int | None = None,
                    kv_int8: bool = False) -> jax.Array:
    """Stochastic decode: temperature / top-k / top-p (nucleus)
    sampling over the same scanned KV-cache loop as
    :func:`greedy_generate`.  ``top_k=0`` disables the k-truncation;
    ``top_p=1.0`` disables nucleus truncation; both together reduce to
    plain temperature sampling (no per-step sort at all).
    Deterministic per ``key``."""
    t = prompt.shape[1]
    max_len = _validate_rollout(cfg, t, n_steps, max_len)
    if not 0 <= top_k <= cfg.vocab_size:
        raise ValueError(f"top_k {top_k} not in [0, vocab]")
    if not 0.0 < top_p:
        # top_p <= 0 would mask EVERY token; the argmax that comes out
        # is a float-absorption accident, not a contract — reject it
        raise ValueError(f"top_p must be > 0, got {top_p}")
    if temperature <= 0:
        raise ValueError(
            f"temperature must be > 0, got {temperature} "
            "(use greedy_generate for argmax decoding)")
    return _sample_fn(cfg, t, n_steps, max_len, int(top_k),
                      float(top_p) < 1.0, kv_int8)(
        params, prompt, key,
        jnp.float32(temperature), jnp.float32(top_p))


def _beam_attend(q: jax.Array, pcache: dict, gcache: dict,
                 step_i: jax.Array, layer_idx=None) -> jax.Array:
    """Two-segment beam attention.  q: [B·W, Hq, 1, D].  The PROMPT
    segment (pcache k/v: [B, Hkv, T, D]) is stored once per sequence —
    the W beams of a sequence read the same panel via a batched einsum,
    never a repeated copy.  The GEN segment (gcache k/v: [B·W, Hkv, G,
    D]) is per-beam; rows past ``step_i`` mask out.  Softmax is joint
    across both segments.  int8 caches fold their per-token scales into
    scores (k) and probabilities (v), as in :func:`_cached_attend_q8`."""
    bw, hq, _, d = q.shape
    b, hkv, t_p = pcache["k"].shape[0], pcache["k"].shape[1], \
        pcache["k"].shape[2]
    w = bw // b
    group = hq // hkv
    g_len = gcache["k"].shape[2]
    scale = d ** -0.5
    qp = q.reshape(b, w, hkv, group, d)
    ps = jnp.einsum("bwkgd,bksd->bwkgs", qp,
                    pcache["k"].astype(q.dtype),
                    preferred_element_type=jnp.float32)
    if "k_scale" in pcache:
        ps = ps * pcache["k_scale"][:, None, :, None, :]
    qg = q.reshape(bw, hkv, group, d)
    gs = jnp.einsum("nkgd,nksd->nkgs", qg,
                    gcache["k"].astype(q.dtype),
                    preferred_element_type=jnp.float32)
    if "k_scale" in gcache:
        gs = gs * gcache["k_scale"][:, :, None, :]
    gs = jnp.where(jnp.arange(g_len)[None, None, None, :] <= step_i,
                   gs, NEG_INF)
    allscores = jnp.concatenate(
        [ps.reshape(bw, hkv, group, t_p), gs], axis=-1) * scale
    probs = jax.nn.softmax(allscores, axis=-1)
    pp = probs[..., :t_p].reshape(b, w, hkv, group, t_p)
    gp = probs[..., t_p:]
    if "v_scale" in pcache:
        pp = pp * pcache["v_scale"][:, None, :, None, :]
    if "v_scale" in gcache:
        gp = gp * gcache["v_scale"][:, :, None, :]
    out = jnp.einsum("bwkgs,bksd->bwkgd", pp,
                     pcache["v"].astype(q.dtype),
                     preferred_element_type=jnp.float32).reshape(
        bw, hkv, group, d)
    out = out + jnp.einsum("nkgs,nksd->nkgd", gp,
                           gcache["v"].astype(q.dtype),
                           preferred_element_type=jnp.float32)
    return out.reshape(bw, hq, 1, d).astype(q.dtype)


def _beam_decode_step(params: dict, tokens: jax.Array, pcache: dict,
                      gcache: dict, step_i: jax.Array, t: int,
                      cfg: LlamaConfig) -> tuple[jax.Array, dict]:
    """One beam decode step over the two-segment cache.  tokens:
    [B·W] at global position t + step_i.  Writes ONLY the gen segment
    (shared offset ``step_i`` — a plain dynamic_update_slice, no
    scatter); returns (logits [B·W, V] f32, updated gen cache)."""
    bw = tokens.shape[0]
    kv_int8 = "k_scale" in gcache
    x = jnp.take(params["embed"], tokens, axis=0)[:, None, :]
    positions = jnp.broadcast_to(t + step_i, (bw, 1))

    def layer(x, xs):
        if kv_int8:
            lp, pk, pv, pks, pvs, gk, gv, gks, gvs = xs
            pc = {"k": pk, "v": pv, "k_scale": pks, "v_scale": pvs}
        else:
            lp, pk, pv, gk, gv = xs
            pc = {"k": pk, "v": pv}
        h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(h, lp, cfg, positions)
        if kv_int8:
            kq, ks = _quantize_rows(k)
            vq, vs = _quantize_rows(v)
            gk = lax.dynamic_update_slice(gk, kq, (0, 0, step_i, 0))
            gv = lax.dynamic_update_slice(gv, vq, (0, 0, step_i, 0))
            gks = lax.dynamic_update_slice(gks, ks, (0, 0, step_i))
            gvs = lax.dynamic_update_slice(gvs, vs, (0, 0, step_i))
            gc = {"k": gk, "v": gv, "k_scale": gks, "v_scale": gvs}
            new = (gk, gv, gks, gvs)
        else:
            gk = lax.dynamic_update_slice(
                gk, k.astype(gk.dtype), (0, 0, step_i, 0))
            gv = lax.dynamic_update_slice(
                gv, v.astype(gv.dtype), (0, 0, step_i, 0))
            gc = {"k": gk, "v": gv}
            new = (gk, gv)
        o = _beam_attend(q, pc, gc, step_i)
        return _attn_finish(x, o, lp, cfg,
                            lambda x_, lp_: _dense_ffn(x_, lp_, cfg)), new

    if kv_int8:
        xs = (params["layers"], pcache["k"], pcache["v"],
              pcache["k_scale"], pcache["v_scale"],
              gcache["k"], gcache["v"], gcache["k_scale"],
              gcache["v_scale"])
        x, (gk, gv, gks, gvs) = lax.scan(layer, x, xs)
        gcache = {"k": gk, "v": gv, "k_scale": gks, "v_scale": gvs}
    else:
        xs = (params["layers"], pcache["k"], pcache["v"],
              gcache["k"], gcache["v"])
        x, (gk, gv) = lax.scan(layer, x, xs)
        gcache = {"k": gk, "v": gv}
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits[:, 0], gcache


@functools.lru_cache(maxsize=64)
def _beam_fn(cfg: LlamaConfig, t: int, n_steps: int,
             beams: int, kv_int8: bool):
    """Compiled beam-search executable over a TWO-SEGMENT cache: the
    prompt K/V stays [L, B, Hkv, T, D] — shared by a sequence's W
    beams physically, not by copy (W× less prompt-cache HBM) — and
    only the [L, B·W, Hkv, n_steps, D] gen segment is gathered when
    beams reorder.  r2 gathered the WHOLE [.., max_len, ..] cache per
    emitted token (VERDICT r2 weak #6: traffic scaled with max_len,
    not written length); the gen-only gather scales with n_steps."""

    @jax.jit
    def run(params, prompt):
        b = prompt.shape[0]
        # prefill ONCE on [B, T], cache sized exactly to the prompt —
        # this IS the shared prompt segment
        logits, pcache = prefill(params, prompt, cfg, t,
                                 kv_int8=kv_int8)
        gcache = init_kv_cache(cfg, b * beams, max(n_steps - 1, 1),
                               kv_int8=kv_int8)
        first_lp = jax.nn.log_softmax(logits, axis=-1)  # [B, V]
        v = first_lp.shape[-1]
        # initial frontier: the top W distinct first tokens
        scores, first_tok = lax.top_k(first_lp, beams)  # [B, W]
        tokens0 = first_tok.reshape(b * beams).astype(prompt.dtype)

        def step(carry, i):
            scores, token, gcache, out = carry
            # iteration i consumes the token at global position t+i
            # (tokens0 sits at t), same bookkeeping as _rollout
            logits, gcache = _beam_decode_step(params, token, pcache,
                                               gcache, i, t, cfg)
            logp = jax.nn.log_softmax(logits, axis=-1)  # [B*W, V]
            joint = scores.reshape(b, beams, 1) \
                + logp.reshape(b, beams, v)             # [B, W, V]
            flat = joint.reshape(b, beams * v)
            scores, idx = lax.top_k(flat, beams)        # [B, W]
            src_beam = idx // v                         # [B, W] in [0,W)
            token = (idx % v).reshape(b * beams).astype(token.dtype)
            # gather surviving beams' GEN rows + running outputs (the
            # prompt segment is beam-invariant: nothing to reorder)
            rows = (jnp.arange(b)[:, None] * beams
                    + src_beam).reshape(b * beams)      # flat batch idx
            gcache = jax.tree.map(lambda c: jnp.take(c, rows, axis=1),
                                  gcache)
            out = jnp.take(out, rows, axis=0)
            out = out.at[:, i + 1].set(token)
            return (scores, token, gcache, out), None

        out0 = jnp.zeros((b * beams, n_steps), prompt.dtype)
        out0 = out0.at[:, 0].set(tokens0)
        (scores, _, _, out), _ = lax.scan(
            step, (scores, tokens0, gcache, out0),
            jnp.arange(n_steps - 1))
        # best beam per sequence (beams are score-sorted by top_k)
        best = out.reshape(b, beams, n_steps)[:, 0]
        return best, scores[:, 0]

    return run


def _attend_buffer_partials(q: jax.Array, bk: jax.Array, bv: jax.Array,
                            j: jax.Array
                            ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Softmax partials over a dense write buffer (valid at buffer
    index <= j, shared across rows).  q: [B, Hq, 1, D]; buffer
    [B, Hkv, stride, D].  Returns (o [B, Hq, D] f32 normalized,
    m [B, Hq], l [B, Hq]) for the flash-decoding merge with the paged
    pool's partials.  Shared by the serve engine's in-block buffer and
    the paged beam path's gen segment."""
    b, hq, t, d = q.shape
    hkv, stride = bk.shape[1], bk.shape[2]
    qg = q.reshape(b, hkv, hq // hkv, d)
    s = jnp.einsum("bkgd,bksd->bkgs", qg, bk,
                   preferred_element_type=jnp.float32) * (d ** -0.5)
    mask = (jnp.arange(stride) <= j)[None, None, None, :]
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    w = jnp.where(mask, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(w, axis=-1)
    o = jnp.einsum("bkgs,bksd->bkgd", w.astype(bv.dtype), bv,
                   preferred_element_type=jnp.float32)
    o = o / jnp.maximum(l, 1e-30)[..., None]
    return (o.reshape(b, hq, d), m.reshape(b, hq), l.reshape(b, hq))


def _beam_paged_decode_step(params: dict, tokens: jax.Array, pool: dict,
                            pt: jax.Array, tvec: jax.Array,
                            gcache: dict, step_i: jax.Array, t: int,
                            beams: int, cfg: LlamaConfig,
                            interpret: bool) -> tuple[jax.Array, dict]:
    """One beam decode step with the PROMPT segment on the paged pool.

    The beams of a sequence fold into the paged kernel's q-GROUP dim:
    the kernel runs B programs (one per sequence), each reading its
    prompt pages ONCE from the pool for all W beams' queries — the
    two-segment design's shared-prompt read, kept, while the prompt
    K/V lives in pool pages aliased by every beam (VERDICT r4 weak #6:
    beam was stuck on the dense cache).  The small per-beam GEN
    segment stays a dense [B·W, Hkv, G, D] buffer (exactly the serve
    engine's write-buffer shape) and merges via flash-decoding
    partials."""
    from kubegpu_tpu.ops.paged_attention import (
        merge_partials,
        paged_attention,
    )
    bw = tokens.shape[0]
    b = bw // beams
    hkv = cfg.n_kv_heads
    group = cfg.n_heads // hkv
    hd = cfg.head_dim
    x = jnp.take(params["embed"], tokens, axis=0)[:, None, :]
    positions = jnp.broadcast_to(t + step_i, (bw, 1))
    d0 = jnp.zeros((b,), jnp.int32)    # no flushed decode region
    lidx = jnp.arange(cfg.n_layers, dtype=jnp.int32)

    def layer(x, xs):
        lp, gk, gv, li = xs
        h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(h, lp, cfg, positions)   # [B·W, Hq, 1, D]
        gk = lax.dynamic_update_slice(gk, k.astype(gk.dtype),
                                      (0, 0, step_i, 0))
        gv = lax.dynamic_update_slice(gv, v.astype(gv.dtype),
                                      (0, 0, step_i, 0))
        # fold beams into the group dim: [B·W, Hq, D] → [B, Hkv, W·g, D]
        qp = q[:, :, 0, :].reshape(b, beams, hkv, group, hd) \
            .transpose(0, 2, 1, 3, 4) \
            .reshape(b, hkv * beams * group, hd)
        o_p, m_p, l_p = paged_attention(
            qp, pool["k"], pool["v"], pt, li, tvec, tvec, d0,
            interpret=interpret)
        def unfold(a):
            back = a.reshape(b, hkv, beams, group, *a.shape[2:])
            return back.transpose(0, 2, 1, 3, *range(4, back.ndim)) \
                .reshape(bw, hkv * group, *a.shape[2:])
        o_p, m_p, l_p = unfold(o_p), unfold(m_p), unfold(l_p)
        o_b, m_b, l_b = _attend_buffer_partials(q, gk, gv, step_i)
        o = merge_partials(o_p, m_p, l_p, o_b, m_b, l_b)
        o = o[:, :, None, :].astype(x.dtype)
        return _attn_finish(
            x, o, lp, cfg,
            lambda x_, lp_: _dense_ffn(x_, lp_, cfg)), (gk, gv)

    x, (gk_new, gv_new) = lax.scan(
        layer, x, (params["layers"], gcache["k"], gcache["v"], lidx))
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits[:, 0], {"k": gk_new, "v": gv_new}


@functools.lru_cache(maxsize=64)
def _beam_paged_fn(cfg: LlamaConfig, t: int, n_steps: int, beams: int,
                   page_size: int, interpret: bool):
    """Beam search with the prompt segment in a page pool.  The pool is
    built from the prefill panel inside the jit (B × ceil(t/P) pages
    plus trash page 0 — the same layout the serve engine's pool uses),
    and every decode step's prompt attention runs through the REAL
    paged-attention kernel via per-sequence page tables that all W
    beams alias.  Reorders gather only the dense gen segment, as in
    :func:`_beam_fn` — pages never move."""
    n_pp = -(-t // page_size)
    bucket = n_pp * page_size

    @jax.jit
    def run(params, prompt):
        b = prompt.shape[0]
        # prefill into a page-aligned panel, then view it AS the pool:
        # [L, B, Hkv, bucket, D] → [L, 1 + B·n_pp, Hkv, P, D]
        logits, pcache = prefill(params, prompt, cfg, bucket)
        L, _, hkv, _, hd = pcache["k"].shape

        def paginate(panel):
            pages = panel.reshape(L, b, hkv, n_pp, page_size, hd) \
                .transpose(0, 1, 3, 2, 4, 5) \
                .reshape(L, b * n_pp, hkv, page_size, hd)
            trash = jnp.zeros((L, 1, hkv, page_size, hd), pages.dtype)
            return jnp.concatenate([trash, pages], axis=1)

        pool = {"k": paginate(pcache["k"]), "v": paginate(pcache["v"])}
        pt = (1 + jnp.arange(b)[:, None] * n_pp
              + jnp.arange(n_pp)[None, :]).astype(jnp.int32)
        tvec = jnp.full((b,), t, jnp.int32)
        gcache = init_kv_cache(cfg, b * beams, max(n_steps - 1, 1))
        first_lp = jax.nn.log_softmax(logits, axis=-1)
        v = first_lp.shape[-1]
        scores, first_tok = lax.top_k(first_lp, beams)
        tokens0 = first_tok.reshape(b * beams).astype(prompt.dtype)

        def step(carry, i):
            scores, token, gcache, out = carry
            logits, gcache = _beam_paged_decode_step(
                params, token, pool, pt, tvec, gcache, i, t, beams,
                cfg, interpret)
            logp = jax.nn.log_softmax(logits, axis=-1)
            joint = scores.reshape(b, beams, 1) \
                + logp.reshape(b, beams, v)
            flat = joint.reshape(b, beams * v)
            scores, idx = lax.top_k(flat, beams)
            src_beam = idx // v
            token = (idx % v).reshape(b * beams).astype(token.dtype)
            rows = (jnp.arange(b)[:, None] * beams
                    + src_beam).reshape(b * beams)
            gcache = jax.tree.map(lambda c: jnp.take(c, rows, axis=1),
                                  gcache)
            out = jnp.take(out, rows, axis=0)
            out = out.at[:, i + 1].set(token)
            return (scores, token, gcache, out), None

        out0 = jnp.zeros((b * beams, n_steps), prompt.dtype)
        out0 = out0.at[:, 0].set(tokens0)
        (scores, _, _, out), _ = lax.scan(
            step, (scores, tokens0, gcache, out0),
            jnp.arange(n_steps - 1))
        best = out.reshape(b, beams, n_steps)[:, 0]
        return best, scores[:, 0]

    return run


def beam_generate_paged(params: dict, prompt: jax.Array, n_steps: int,
                        cfg: LlamaConfig, beams: int = 4,
                        page_size: int = 128,
                        max_len: int | None = None
                        ) -> tuple[jax.Array, jax.Array]:
    """:func:`beam_generate` with the prompt K/V on a page pool read by
    the pallas paged-attention kernel (beams alias their sequence's
    pages; the kernel reads each page once per sequence, not per
    beam).  Same return contract and scoring as the dense version."""
    max_len = _validate_rollout(cfg, prompt.shape[1], n_steps, max_len)
    if not 1 <= beams <= cfg.vocab_size:
        raise ValueError(
            f"beams must be in [1, vocab_size={cfg.vocab_size}], "
            f"got {beams}")
    interpret = jax.devices()[0].platform == "cpu"
    return _beam_paged_fn(cfg, prompt.shape[1], n_steps, beams,
                          page_size, interpret)(params, prompt)


def beam_generate(params: dict, prompt: jax.Array, n_steps: int,
                  cfg: LlamaConfig, beams: int = 4,
                  max_len: int | None = None,
                  kv_int8: bool = False
                  ) -> tuple[jax.Array, jax.Array]:
    """Beam search over the KV-cache decode loop: returns (tokens
    [B, n_steps] — the best beam per sequence — and its total
    log-probability [B]).  Length-agnostic scoring (sum of logprobs;
    all beams have equal length here, so no normalization is needed)."""
    max_len = _validate_rollout(cfg, prompt.shape[1], n_steps, max_len)
    if not 1 <= beams <= cfg.vocab_size:
        raise ValueError(
            f"beams must be in [1, vocab_size={cfg.vocab_size}], "
            f"got {beams}")
    # max_len validates the caller's length contract but no longer
    # sizes anything: the two-segment cache is exactly (t, n_steps-1)
    return _beam_fn(cfg, prompt.shape[1], n_steps, beams,
                    kv_int8)(params, prompt)


def generate(params: dict, prompt: jax.Array, n_steps: int,
             cfg: LlamaConfig, max_len: int | None = None,
             kv_int8: bool = False, ffn_factory=None,
             ffn_cfg=None) -> jax.Array:
    """Public greedy entry point with the feed-forward override hook:
    ``ffn_factory(ffn_cfg)`` (both hashable — they key the compile
    cache) builds an ``ffn(x, lp) -> x`` replacing the dense SwiGLU —
    this is how other families (MoE's routed experts) ride the shared
    rollout/compile-cache machinery without reaching into privates."""
    t = prompt.shape[1]
    max_len = _validate_rollout(cfg, t, n_steps, max_len)
    return _generate_fn(cfg, t, n_steps, max_len, kv_int8,
                        ffn_factory=ffn_factory,
                        ffn_cfg=ffn_cfg)(params, prompt)


def greedy_generate(params: dict, prompt: jax.Array, n_steps: int,
                    cfg: LlamaConfig,
                    max_len: int | None = None,
                    kv_int8: bool = False) -> jax.Array:
    """Greedy decode ``n_steps`` tokens after ``prompt`` [B, T] — prefill
    plus one scanned decode loop, all inside a single jit.  Returns the
    generated tokens [B, n_steps].  ``kv_int8`` stores the cache as
    int8 with per-token scales (half the cache HBM traffic — the
    dominant decode cost at wide batches)."""
    return generate(params, prompt, n_steps, cfg, max_len=max_len,
                    kv_int8=kv_int8)


def truncate_at_eos(tokens: list, eos_id: int | None) -> bool:
    """Trim a generated-token list IN PLACE at its first EOS
    (inclusive, so the terminator is returned to the caller like any
    other token).  Returns True iff an EOS was found — the serving
    engine's finish signal, shared by its K=1 and fused consume paths
    so both retire a request on exactly the same token."""
    if eos_id is None:
        return False
    try:
        i = tokens.index(eos_id)
    except ValueError:
        return False
    del tokens[i + 1:]
    return True


# ---------------------------------------------------------------------------
# Speculative decoding (greedy, early-exit self-draft)
# ---------------------------------------------------------------------------

def draft_view(params: dict, draft_layers: int) -> dict:
    """The first ``draft_layers`` of a stacked-layer tree as a model of
    their own (early-exit self-draft — no extra parameters): slice the
    stacked leaves, share embed/final_norm/lm_head.

    Slicing copies the draft fraction of the weights, so loops must
    call this ONCE and reuse the view: the serving engine caches it at
    construction (``ContinuousBatcher._draft_params``) and the bench
    rows build one view per window — never one per call."""
    return {
        "embed": params["embed"],
        # tree_map, not dict-comprehension slicing: leaves may be
        # QTensors (int8 weights), whose pytree children ([L,...] values
        # and [L,1,out] scales) slice in lockstep under tree.map
        "layers": jax.tree.map(lambda a: a[:draft_layers],
                               params["layers"]),
        "final_norm": params["final_norm"],
        "lm_head": params["lm_head"],
    }


def spec_acceptance(drafted: jax.Array, full: jax.Array,
                    cap) -> tuple[jax.Array, jax.Array]:
    """THE speculative acceptance rule, shared by every spec path (the
    host loop, the fused loop, and the serving engine's batched verify
    tick): ``drafted`` [B, γ] proposals vs ``full`` [B, >=γ] full-model
    argmaxes at the same positions.  Returns ``(matched, take)`` [B]
    int32 — the longest matching prefix per element, and that prefix
    capped by ``cap`` (a scalar for the lockstep loops' γ-1 draft-hole
    cap, a [B] vector for the engine's per-slot adaptive γ).  A token
    is only ever emitted if the FULL model argmaxed it, so any cap is
    a throughput knob, never a correctness one."""
    g = drafted.shape[1]
    match = (drafted == full[:, :g]).astype(jnp.int32)
    matched = jnp.cumprod(match, axis=1).sum(axis=1)
    return matched, jnp.minimum(matched, cap)


@functools.lru_cache(maxsize=32)
def _spec_fns(cfg: LlamaConfig, draft_layers: int, kv_int8: bool):
    """Jitted pieces of the speculative loop, cached per static
    signature: the draft's single-token step and the full model's
    chunked verify (one executable per chunk length)."""
    import dataclasses

    dcfg = dataclasses.replace(cfg, n_layers=draft_layers)

    @jax.jit
    def draft_step(dparams, cache, token, pos):
        return decode_step(dparams, cache, token, pos, dcfg)

    @jax.jit
    def verify(params, cache, chunk, pos):
        return _forward_with_cache(params, chunk, cache, pos, cfg)

    @functools.partial(jax.jit, static_argnames=("max_len", "full"))
    def do_prefill(p, prompt, max_len, full):
        return prefill(p, prompt, cfg if full else dcfg, max_len,
                       kv_int8=kv_int8)

    return dcfg, draft_step, verify, do_prefill


def spec_generate(params: dict, prompt: jax.Array, n_steps: int,
                  cfg: LlamaConfig, draft_layers: int, gamma: int = 4,
                  max_len: int | None = None, kv_int8: bool = False,
                  dparams: dict | None = None
                  ) -> tuple[jax.Array, dict]:
    """Greedy speculative decoding: the first ``draft_layers`` of the
    model propose ``gamma`` tokens autoregressively, then ONE chunked
    full-model forward verifies them; the longest matching prefix is
    accepted and the full model's argmax at the first mismatch is the
    (always-valid) correction token.

    **Output is identical to greedy_generate by construction** — the
    draft only decides how many tokens each full forward yields, never
    which.  The caveat is numerical, not algorithmic: every emitted
    token is the FULL model's argmax, but computed by a chunked
    (T=γ+1) executable instead of greedy's stepwise one; in bf16 the
    two can round logits differently, so a near-degenerate argmax tie
    (untrained weights) may flip a token.  Bit-exact in f32 (asserted
    in tests); measured 47/48 identical on the bf16 bench model with
    random weights.  Batched elements run in lockstep on the MINIMUM
    acceptance
    across the batch (truncating an accepted prefix keeps it valid).
    Stale cache rows past an accepted prefix are overwritten by the
    next chunk before any query can attend them (the cached forward
    writes each layer's K/V before attending).

    Returns (tokens [B, n_steps], stats) where stats carries
    ``iterations`` (full-model forwards spent) and ``acceptance_rate``
    (mean accepted draft tokens per proposal slot).  The speedup is
    acceptance-dependent: ~(accepted+1) tokens per full forward against
    (draft_layers/n_layers)·gamma extra draft compute.  The outer loop
    is host-side (data-dependent acceptance); each iteration is a few
    dispatches."""
    import numpy as np

    t = prompt.shape[1]
    max_len = _validate_rollout(cfg, t, n_steps, max_len)
    if not 1 <= draft_layers <= cfg.n_layers:
        raise ValueError(
            f"draft_layers {draft_layers} not in [1, {cfg.n_layers}]")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    dcfg, draft_step, verify, do_prefill = _spec_fns(
        cfg, draft_layers, kv_int8)
    if dparams is None:
        # serving loops should build this ONCE via draft_view() and
        # pass it in — slicing re-copies the draft fraction of the
        # weights per call
        dparams = draft_view(params, draft_layers)

    logits, full_cache = do_prefill(params, prompt, max_len, True)
    _, draft_cache = do_prefill(dparams, prompt, max_len, False)
    cur = jnp.argmax(logits, axis=-1).astype(prompt.dtype)
    out = [cur]
    pos = t            # global position of `cur`
    iterations = 0
    proposed = accepted_total = 0
    while len(out) < n_steps:
        remaining = n_steps - len(out)
        if remaining == 1:
            # a draft proposal can't help (take caps at 0): one plain
            # full-model step, reusing the T=1 verify executable
            vlogits, full_cache = verify(params, full_cache,
                                         cur[:, None], jnp.int32(pos))
            out.append(jnp.argmax(vlogits[:, 0], axis=-1)
                       .astype(cur.dtype))
            iterations += 1
            break
        g = min(gamma, remaining)
        # draft proposes g tokens from `cur`
        d_toks = []
        dtok = cur
        for i in range(g):
            dlogits, draft_cache = draft_step(
                dparams, draft_cache, dtok, jnp.int32(pos + i))
            dtok = jnp.argmax(dlogits, axis=-1).astype(cur.dtype)
            d_toks.append(dtok)
        # one full-model forward over [cur, d_1..d_g]
        chunk = jnp.stack([cur] + d_toks, axis=1)     # [B, g+1]
        vlogits, full_cache = verify(params, full_cache, chunk,
                                     jnp.int32(pos))
        f = jnp.argmax(vlogits, axis=-1)              # [B, g+1]
        drafted = jnp.stack(d_toks, axis=1)           # [B, g]
        per_elem, _ = spec_acceptance(drafted, f, g)  # cap applied below
        j = int(np.asarray(per_elem.min()))           # lockstep accept
        # cap at g-1: the g-th draft token was never PROCESSED by the
        # draft (only proposed), so accepting it would leave a hole in
        # the draft cache; when all g match, the g-th draft is emitted
        # anyway as the "correction" (f[:, g-1] == d_g by the match) —
        # same tokens, contiguous caches
        take = min(j, g - 1, n_steps - len(out) - 1)
        out.extend(d_toks[:take])
        cur = f[:, take].astype(cur.dtype)            # correction/next
        out.append(cur)
        pos += take + 1
        iterations += 1
        # g-1, not g: `take` is capped at g-1 (the g-th draft token is
        # only ever emitted as the "correction"), so g-1 is the number
        # of slots that can actually be accepted — with g as the
        # denominator a perfect draft reported at most (g-1)/g
        proposed += g - 1
        accepted_total += take
    tokens = jnp.stack(out[:n_steps], axis=1)
    stats = {
        "iterations": iterations,
        "acceptance_rate": (accepted_total / proposed) if proposed else 0.0,
    }
    return tokens, stats


@functools.lru_cache(maxsize=32)
def _spec_fused_fn(cfg: LlamaConfig, t: int, n_steps: int, max_len: int,
                   draft_layers: int, gamma: int, kv_int8: bool):
    """One compiled executable for the ENTIRE speculative generation:
    draft + verify + acceptance inside a ``lax.while_loop``.  The
    host-loop :func:`spec_generate` pays a host round trip per
    iteration for the data-dependent acceptance (``per_elem.min()``) —
    under the async TPU tunnel that RTT dwarfs the decode step itself,
    and even locally it serializes dispatch.  Here acceptance stays on
    device: each iteration emits a fixed-width (γ+1) token slab at a
    dynamic offset (accepted prefix + correction, tail slots carry the
    correction as filler) and the next iteration's slab starts exactly
    after the accepted prefix, overwriting the filler."""
    import dataclasses

    dcfg = dataclasses.replace(cfg, n_layers=draft_layers)
    # verify chunks write cache rows up to pos+γ — up to γ-1 past the
    # last emitted token — so the cache over-allocates by γ
    clen = max_len + gamma
    width = n_steps + gamma + 1   # out buffer: final slab may overhang

    @jax.jit
    def run(params, dparams, prompt):
        b = prompt.shape[0]
        logits, fcache = prefill(params, prompt, cfg, clen,
                                 kv_int8=kv_int8)
        _, dcache = prefill(dparams, prompt, dcfg, clen,
                            kv_int8=kv_int8)
        cur = jnp.argmax(logits, axis=-1).astype(prompt.dtype)
        out = jnp.zeros((b, width), prompt.dtype).at[:, 0].set(cur)
        slots = jnp.arange(gamma + 1)

        def cond(c):
            return c[1] < n_steps

        def body(c):
            out, n_out, cur, pos, fcache, dcache, iters, acc, prop = c

            def dstep(carry, i):
                tok, dc = carry
                dlogits, dc = decode_step(dparams, dc, tok, pos + i,
                                          dcfg)
                nxt = jnp.argmax(dlogits, axis=-1).astype(tok.dtype)
                return (nxt, dc), nxt

            (_, dcache), drafted = lax.scan(dstep, (cur, dcache),
                                            jnp.arange(gamma))
            drafted = drafted.swapaxes(0, 1)                 # [B, γ]
            chunk = jnp.concatenate([cur[:, None], drafted], axis=1)
            vlogits, fcache = _forward_with_cache(params, chunk, fcache,
                                                  pos, cfg)
            f = jnp.argmax(vlogits, axis=-1).astype(cur.dtype)
            # lockstep accept: min over batch; cap γ-1 (the γ-th draft
            # was never processed by the draft model — it re-emerges as
            # the correction when all match) and the remaining budget
            matched, _ = spec_acceptance(drafted, f, gamma)
            j = matched.min()
            take = jnp.minimum(jnp.minimum(j, gamma - 1),
                               n_steps - n_out - 1)
            corr = lax.dynamic_index_in_dim(f, take, axis=1,
                                            keepdims=False)  # [B]
            padded = jnp.concatenate([drafted, drafted[:, -1:]], axis=1)
            emit = jnp.where(slots[None, :] < take, padded,
                             corr[:, None])                  # [B, γ+1]
            out = lax.dynamic_update_slice(out, emit, (0, n_out))
            # acceptable slots this iteration, mirroring the host
            # loop's g = min(gamma, remaining); proposed += g - 1 —
            # keeps acceptance_rate identical between the two paths
            # even when the budget truncates the final slab
            prop_i = jnp.minimum(gamma, n_steps - n_out) - 1
            return (out, n_out + take + 1, corr, pos + take + 1,
                    fcache, dcache, iters + 1, acc + take,
                    prop + prop_i)

        init = (out, jnp.int32(1), cur, jnp.int32(t), fcache, dcache,
                jnp.int32(0), jnp.int32(0), jnp.int32(0))
        out, _, _, _, _, _, iters, acc, prop = lax.while_loop(
            cond, body, init)
        return out[:, :n_steps], iters, acc, prop

    return run


@functools.lru_cache(maxsize=32)
def _pld_fused_fn(cfg: LlamaConfig, t: int, n_steps: int, max_len: int,
                  gamma: int, ngram: int, kv_int8: bool):
    """Prompt-lookup (n-gram) speculative decoding, fully on-device.

    The draft source is the sequence ITSELF: propose the γ tokens that
    followed the most recent earlier occurrence of the current
    trailing ``ngram``.  No draft model, no draft cache — the entire
    draft cost is integer compares over the token buffer, so every
    iteration costs ONE chunked (γ+1) full-model forward; at decode
    batch sizes that forward is weight-read bound and costs barely
    more than a single-token step, which is why this wins wherever
    the text repeats (VERDICT r3 next-item #3: the layer-slice
    self-draft could never beat greedy on an untrained model — its
    acceptance was 0 while its draft steps still cost real forwards).

    Emitted tokens are the full model's argmax by construction, same
    as :func:`spec_generate_fused` (the lookup only decides how many
    tokens each forward yields, never which) — bit-exact vs greedy in
    f32, the usual chunked-vs-stepwise bf16 tie caveat applies."""
    clen = max_len + gamma
    width = n_steps + gamma + 1
    seqlen = t + width   # prompt + out view; pos+γ always within it
    slots = jnp.arange(gamma + 1)

    @jax.jit
    def run(params, prompt):
        b = prompt.shape[0]
        logits, fcache = prefill(params, prompt, cfg, clen,
                                 kv_int8=kv_int8)
        cur = jnp.argmax(logits, axis=-1).astype(prompt.dtype)
        out = jnp.zeros((b, width), prompt.dtype).at[:, 0].set(cur)

        def lookup(seq, pos):
            """Latest position i < pos whose ngram-window (ending at i)
            equals the window ending at pos; returns drafted [B, γ]
            (continuation after the match; repeats of the current token
            when no match exists — rarely accepted, statically shaped)."""
            w = jax.vmap(
                lambda s: lax.dynamic_slice(s, (pos - ngram + 1,),
                                            (ngram,)))(seq)   # [B, n]
            m = jnp.ones(seq.shape, bool)
            for k in range(ngram):
                shift = ngram - 1 - k
                shifted = jnp.pad(seq, ((0, 0), (shift, 0)))[:, :seqlen] \
                    if shift else seq
                m &= shifted == w[:, k:k + 1]
            i = jnp.arange(seqlen)[None, :]
            cand = (i >= ngram - 1) & (i < pos)
            i_match = jnp.max(jnp.where(m & cand, i, -1), axis=1)  # [B]
            found = i_match >= 0
            start = jnp.maximum(i_match + 1, 0)
            cont = jax.vmap(
                lambda s, st: lax.dynamic_slice(s, (st,), (gamma,)))(
                seq, start)
            last = jax.vmap(
                lambda s: lax.dynamic_slice(s, (pos,), (1,)))(seq)
            return jnp.where(found[:, None], cont,
                             jnp.broadcast_to(last, cont.shape))

        def cond(c):
            return c[1] < n_steps

        def body(c):
            out, n_out, cur, pos, fcache, iters, acc, prop = c
            # sequence view: prompt then emitted tokens (cur sits at
            # sequence index pos = t + n_out - 1)
            seq = jnp.concatenate([prompt, out], axis=1)
            drafted = lookup(seq, pos)                      # [B, γ]
            chunk = jnp.concatenate([cur[:, None], drafted], axis=1)
            vlogits, fcache = _forward_with_cache(params, chunk, fcache,
                                                  pos, cfg)
            f = jnp.argmax(vlogits, axis=-1).astype(cur.dtype)
            match = (drafted == f[:, :gamma]).astype(jnp.int32)
            # lockstep accept (min over batch).  Unlike the self-draft
            # path there is NO γ-1 cap: the lookup has no cache to keep
            # consistent, and when all γ drafts match, f[:, γ] is the
            # model's own next token — a full γ+1 tokens per forward.
            j = jnp.cumprod(match, axis=1).sum(axis=1).min()
            take = jnp.minimum(j, n_steps - n_out - 1)
            corr = lax.dynamic_index_in_dim(f, take, axis=1,
                                            keepdims=False)  # [B]
            padded = jnp.concatenate([drafted, drafted[:, -1:]], axis=1)
            emit = jnp.where(slots[None, :] < take, padded,
                             corr[:, None])                  # [B, γ+1]
            out = lax.dynamic_update_slice(out, emit, (0, n_out))
            prop_i = jnp.minimum(gamma, n_steps - n_out - 1)
            return (out, n_out + take + 1, corr, pos + take + 1,
                    fcache, iters + 1, acc + take, prop + prop_i)

        init = (out, jnp.int32(1), cur, jnp.int32(t), fcache,
                jnp.int32(0), jnp.int32(0), jnp.int32(0))
        out, _, _, _, _, iters, acc, prop = lax.while_loop(
            cond, body, init)
        return out[:, :n_steps], iters, acc, prop

    return run


def _chunk_causal_partials(q: jax.Array, k: jax.Array, v: jax.Array
                           ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Causal softmax partials of a verify chunk over its OWN keys.
    q: [B, Hq, C, D]; k/v: [B, Hkv, C, D].  Returns flattened
    (o [B, Hq·C, D] normalized f32, m, l [B, Hq·C]) in the
    (hkv, group, c)-major order the paged kernel's folded-group
    output uses, so the two merge positionally."""
    b, hq, c, d = q.shape
    hkv = k.shape[1]
    g = hq // hkv
    qg = q.reshape(b, hkv, g, c, d)
    s = jnp.einsum("bkgcd,bksd->bkgcs", qg, k.astype(q.dtype),
                   preferred_element_type=jnp.float32) * (d ** -0.5)
    causal = (jnp.arange(c)[:, None] >= jnp.arange(c)[None, :])
    s = jnp.where(causal[None, None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)
    w = jnp.where(causal[None, None, None],
                  jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(w, axis=-1)
    o = jnp.einsum("bkgcs,bksd->bkgcd", w.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    o = o / jnp.maximum(l, 1e-30)[..., None]
    return (o.reshape(b, hq * c, d), m.reshape(b, hq * c),
            l.reshape(b, hq * c))


def _paged_chunk_forward(params: dict, chunk: jax.Array, pool: dict,
                         pt: jax.Array, pos, cfg: LlamaConfig,
                         page_size: int, npg_row: int,
                         interpret: bool) -> tuple[jax.Array, dict]:
    """The speculative verify forward with the KV history on a page
    pool: the chunk's C=γ+1 queries FOLD into the paged kernel's group
    dim (their history validity [0, pos) is uniform — in-chunk
    causality lives in :func:`_chunk_causal_partials` and merges via
    flash-decoding partials), and the chunk's fresh K/V lands in a
    static 2-page window at offset ``pos`` (each row's pages are
    pool-contiguous, so the window is two ``dynamic_update_slice``
    pages — rejected entries simply stay masked by the next
    iteration's smaller ``d``).  Returns (logits [B, C, V], pool')."""
    from kubegpu_tpu.ops.paged_attention import (
        fold_chunk_queries,
        merge_partials,
        paged_attention,
    )
    b, c = chunk.shape
    hkv = cfg.n_kv_heads
    hd = cfg.head_dim
    p = page_size
    x = jnp.take(params["embed"], chunk, axis=0)
    q_pos = pos + jnp.arange(c)
    positions = jnp.broadcast_to(q_pos[None, :], (b, c))
    d0 = jnp.full((b,), pos, jnp.int32)
    zeros_b = jnp.zeros((b,), jnp.int32)
    off = pos % p
    page_a = pos // p

    def layer(x, xs):
        lp, pk, pv = xs            # this layer's [n_pool, Hkv, P, D]
        h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(h, lp, cfg, positions)   # [B, H, C, D]

        def wrow(r, kv):
            pk, pv = kv
            base = 1 + r * npg_row + page_a

            def put(pw, seg):
                win = lax.dynamic_slice(pw, (base, 0, 0, 0),
                                        (2, hkv, p, hd))
                win = win.transpose(1, 0, 2, 3).reshape(hkv, 2 * p, hd)
                win = lax.dynamic_update_slice(
                    win, seg.astype(win.dtype), (0, off, 0))
                win = win.reshape(hkv, 2, p, hd).transpose(1, 0, 2, 3)
                return lax.dynamic_update_slice(pw, win,
                                                (base, 0, 0, 0))

            return put(pk, k[r]), put(pv, v[r])

        pk, pv = lax.fori_loop(0, b, wrow, (pk, pv))
        qflat = fold_chunk_queries(q)               # (hkv, g, c)-major
        o_p, m_p, l_p = paged_attention(
            qflat, pk[None], pv[None], pt, jnp.int32(0), zeros_b,
            zeros_b, d0, interpret=interpret)
        o_c, m_c, l_c = _chunk_causal_partials(q, k, v)
        o = merge_partials(o_p, m_p, l_p, o_c, m_c, l_c)
        o = o.reshape(b, cfg.n_heads, c, hd).astype(x.dtype)
        return _attn_finish(
            x, o, lp, cfg,
            lambda x_, lp_: _dense_ffn(x_, lp_, cfg)), (pk, pv)

    x, (pk_new, pv_new) = lax.scan(
        layer, x, (params["layers"], pool["k"], pool["v"]))
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, {"k": pk_new, "v": pv_new}


@functools.lru_cache(maxsize=16)
def _pld_paged_fn(cfg: LlamaConfig, t: int, n_steps: int, max_len: int,
                  gamma: int, ngram: int, page_size: int,
                  interpret: bool):
    """:func:`_pld_fused_fn` with the KV history on a page pool —
    the last decode family off the paged regime (VERDICT r4 weak #6).
    Same lookup/accept machinery; the cache machinery is swapped for
    :func:`_paged_chunk_forward` over a pool built from the prefill
    panel (contiguous pages per row + one spare page so the verify
    chunk's 2-page write window never runs off the region)."""
    clen = max_len + gamma
    npg_row = -(-clen // page_size) + 1
    region = npg_row * page_size
    width = n_steps + gamma + 1
    seqlen = t + width
    slots = jnp.arange(gamma + 1)

    @jax.jit
    def run(params, prompt):
        b = prompt.shape[0]
        logits, fcache = prefill(params, prompt, cfg, region)
        L, _, hkv, _, hd = fcache["k"].shape

        def paginate(panel):
            pages = panel.reshape(L, b, hkv, npg_row, page_size, hd) \
                .transpose(0, 1, 3, 2, 4, 5) \
                .reshape(L, b * npg_row, hkv, page_size, hd)
            trash = jnp.zeros((L, 1, hkv, page_size, hd), pages.dtype)
            return jnp.concatenate([trash, pages], axis=1)

        pool = {"k": paginate(fcache["k"]), "v": paginate(fcache["v"])}
        pt = (1 + jnp.arange(b)[:, None] * npg_row
              + jnp.arange(npg_row)[None, :]).astype(jnp.int32)
        cur = jnp.argmax(logits, axis=-1).astype(prompt.dtype)
        out = jnp.zeros((b, width), prompt.dtype).at[:, 0].set(cur)

        def lookup(seq, pos):
            w = jax.vmap(
                lambda s: lax.dynamic_slice(s, (pos - ngram + 1,),
                                            (ngram,)))(seq)
            m = jnp.ones(seq.shape, bool)
            for k_ in range(ngram):
                shift = ngram - 1 - k_
                shifted = jnp.pad(seq, ((0, 0), (shift, 0)))[:, :seqlen] \
                    if shift else seq
                m &= shifted == w[:, k_:k_ + 1]
            i = jnp.arange(seqlen)[None, :]
            cand = (i >= ngram - 1) & (i < pos)
            i_match = jnp.max(jnp.where(m & cand, i, -1), axis=1)
            found = i_match >= 0
            start = jnp.maximum(i_match + 1, 0)
            cont = jax.vmap(
                lambda s, st: lax.dynamic_slice(s, (st,), (gamma,)))(
                seq, start)
            last = jax.vmap(
                lambda s: lax.dynamic_slice(s, (pos,), (1,)))(seq)
            return jnp.where(found[:, None], cont,
                             jnp.broadcast_to(last, cont.shape))

        def cond(c):
            return c[1] < n_steps

        def body(c):
            out, n_out, cur, pos, pool, iters, acc, prop = c
            seq = jnp.concatenate([prompt, out], axis=1)
            drafted = lookup(seq, pos)
            chunk = jnp.concatenate([cur[:, None], drafted], axis=1)
            vlogits, pool = _paged_chunk_forward(
                params, chunk, pool, pt, pos, cfg, page_size, npg_row,
                interpret)
            f = jnp.argmax(vlogits, axis=-1).astype(cur.dtype)
            match = (drafted == f[:, :gamma]).astype(jnp.int32)
            j = jnp.cumprod(match, axis=1).sum(axis=1).min()
            take = jnp.minimum(j, n_steps - n_out - 1)
            corr = lax.dynamic_index_in_dim(f, take, axis=1,
                                            keepdims=False)
            padded = jnp.concatenate([drafted, drafted[:, -1:]], axis=1)
            emit = jnp.where(slots[None, :] < take, padded,
                             corr[:, None])
            out = lax.dynamic_update_slice(out, emit, (0, n_out))
            prop_i = jnp.minimum(gamma, n_steps - n_out - 1)
            return (out, n_out + take + 1, corr, pos + take + 1,
                    pool, iters + 1, acc + take, prop + prop_i)

        init = (out, jnp.int32(1), cur, jnp.int32(t), pool,
                jnp.int32(0), jnp.int32(0), jnp.int32(0))
        out, _, _, _, _, iters, acc, prop = lax.while_loop(
            cond, body, init)
        return out[:, :n_steps], iters, acc, prop

    return run


def pld_generate_paged(params: dict, prompt: jax.Array, n_steps: int,
                       cfg: LlamaConfig, gamma: int = 8,
                       ngram: int = 3, max_len: int | None = None,
                       page_size: int = 128
                       ) -> tuple[jax.Array, dict]:
    """:func:`pld_generate_fused` with the KV history on a page pool
    read by the paged-attention kernel (the chunk's queries fold into
    the kernel's group dim).  Same contract and stats."""
    t = prompt.shape[1]
    max_len = _validate_rollout(cfg, t, n_steps, max_len)
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if ngram < 1:
        raise ValueError(f"ngram must be >= 1, got {ngram}")
    interpret = jax.devices()[0].platform == "cpu"
    toks, iters, acc, prop = _pld_paged_fn(
        cfg, t, n_steps, max_len, gamma, ngram, page_size, interpret)(
        params, prompt)
    import numpy as np
    iters, acc, prop = (int(x) for x in
                        np.asarray(jnp.stack([iters, acc, prop])))
    stats = {
        "iterations": iters,
        "acceptance_rate": (acc / prop) if prop else 0.0,
    }
    return toks, stats


def pld_generate_fused(params: dict, prompt: jax.Array, n_steps: int,
                       cfg: LlamaConfig, gamma: int = 8,
                       ngram: int = 3, max_len: int | None = None,
                       kv_int8: bool = False
                       ) -> tuple[jax.Array, dict]:
    """Prompt-lookup speculative decoding (see :func:`_pld_fused_fn`):
    draft-model-free, wins wherever the generation revisits n-grams of
    its own context (templated text, code edits, summarization);
    degrades to ~greedy cost on non-repetitive text instead of losing
    like a cold self-draft.  Returns (tokens [B, n_steps], stats)."""
    t = prompt.shape[1]
    max_len = _validate_rollout(cfg, t, n_steps, max_len)
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if ngram < 1:
        raise ValueError(f"ngram must be >= 1, got {ngram}")
    toks, iters, acc, prop = _pld_fused_fn(
        cfg, t, n_steps, max_len, gamma, ngram, kv_int8)(params, prompt)
    # ONE host fetch for all three counters — three separate int()
    # casts cost three tunnel round trips (~115 ms each, r4 measured
    # them dwarfing the generation itself in the bench lambda)
    import numpy as np
    iters, acc, prop = (int(x) for x in
                        np.asarray(jnp.stack([iters, acc, prop])))
    stats = {
        "iterations": iters,
        "acceptance_rate": (acc / prop) if prop else 0.0,
    }
    return toks, stats


def spec_generate_fused(params: dict, prompt: jax.Array, n_steps: int,
                        cfg: LlamaConfig, draft_layers: int,
                        gamma: int = 4, max_len: int | None = None,
                        kv_int8: bool = False,
                        dparams: dict | None = None
                        ) -> tuple[jax.Array, dict]:
    """:func:`spec_generate` as a single on-device executable (see
    :func:`_spec_fused_fn`) — same contract, same emitted tokens (every
    token is the full model's argmax), one dispatch for the whole
    generation instead of a host-synced round trip per draft/verify
    iteration.  Stats are fetched once at the end."""
    t = prompt.shape[1]
    max_len = _validate_rollout(cfg, t, n_steps, max_len)
    if not 1 <= draft_layers <= cfg.n_layers:
        raise ValueError(
            f"draft_layers {draft_layers} not in [1, {cfg.n_layers}]")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if dparams is None:
        dparams = draft_view(params, draft_layers)
    toks, iters, acc, prop = _spec_fused_fn(
        cfg, t, n_steps, max_len, draft_layers, gamma, kv_int8)(
        params, dparams, prompt)
    # ONE host fetch for all three counters (see pld_generate_fused)
    import numpy as np
    iters, acc, prop = (int(x) for x in
                        np.asarray(jnp.stack([iters, acc, prop])))
    stats = {
        "iterations": iters,
        "acceptance_rate": (acc / prop) if prop else 0.0,
    }
    return toks, stats

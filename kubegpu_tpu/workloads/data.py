"""Input pipeline for the workload layer — TPU-native data loading.

The reference scheduled jobs and left data loading to the workload
(SURVEY.md §3: no first-party loader); KubeTPU's workloads need the
three standard TPU input-pipeline pieces, built jit/multi-host-clean:

1. :class:`ShardedBatcher` — deterministic, seeded epoch iteration
   where each worker of the gang reads a DISJOINT shard: one global
   permutation per epoch (same on every worker, derived from
   (seed, epoch) only), sliced per worker.  Workers never exchange
   indices and still partition every epoch exactly.
2. :func:`prefetch_to_device` — double-buffered host→device transfer:
   batch N+1's H2D overlaps batch N's compute (the usual hiding of
   PCIe/DMA latency behind the step).
3. :func:`global_batches` — wraps each process's LOCAL batch into a
   global jax.Array laid out by a mesh sharding
   (``jax.make_array_from_process_local_data``), so a dp-sharded
   global batch assembles without any cross-host gather.

Everything is numpy/jax only — real datasets plug in as array sources;
the synthetic sources used by the example workloads live here too.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass
from typing import Iterable, Iterator

import numpy as np


@dataclass(frozen=True)
class Shard:
    """This worker's slice of the gang: ``index`` of ``count``."""
    index: int = 0
    count: int = 1

    def __post_init__(self) -> None:
        if not 0 <= self.index < self.count:
            raise ValueError(f"shard {self.index} not in [0,{self.count})")

    @classmethod
    def from_worker_env(cls, env=None) -> "Shard":
        """From the injected gang env, parsed by the ONE owner of that
        contract (``workloads.programs.distributed.read_env`` — the
        crishim's wiring, SURVEY.md §4.3).  Pass an existing
        ``WorkerEnv`` to avoid re-reading os.environ."""
        if env is None:
            from kubegpu_tpu.workloads.programs.distributed import read_env
            env = read_env()
        return cls(index=env.worker_id, count=env.num_workers)


class ShardedBatcher:
    """Deterministic sharded epoch iteration over array-shaped data.

    ``arrays`` is a dict of equal-leading-dim numpy arrays (features,
    labels, ...).  Per epoch: one global permutation seeded by
    ``(seed, epoch)`` — identical on every worker — is cut into
    per-worker contiguous slices; each worker batches its slice.
    ``drop_remainder`` keeps batch shapes static for jit (the tail
    examples of an epoch are dropped, different ones each epoch thanks
    to the reshuffle)."""

    def __init__(self, arrays: dict[str, np.ndarray], batch_size: int,
                 shard: Shard | None = None, seed: int = 0,
                 shuffle: bool = True, drop_remainder: bool = True):
        if not arrays:
            raise ValueError("arrays must be non-empty")
        sizes = {k: len(v) for k, v in arrays.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"leading dims differ: {sizes}")
        self.arrays = dict(arrays)
        self.n = next(iter(sizes.values()))
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.batch_size = batch_size
        self.shard = shard or Shard()
        if self.n < self.shard.count:
            raise ValueError(
                f"{self.n} examples cannot shard {self.shard.count} ways")
        per = self.n // self.shard.count
        if drop_remainder and per < batch_size:
            # would silently yield ZERO batches every epoch — fail loud
            # at construction with the numbers the operator needs
            raise ValueError(
                f"per-worker shard of {per} examples (n={self.n} / "
                f"{self.shard.count} workers) cannot fill one batch of "
                f"{batch_size} with drop_remainder; shrink the batch or "
                f"the gang")
        self.seed = seed
        self.shuffle = shuffle
        self.drop_remainder = drop_remainder

    def epoch_indices(self, epoch: int) -> np.ndarray:
        """This worker's example indices for ``epoch`` (disjoint across
        workers; the union over workers is all n, minus the per-epoch
        tail that doesn't split evenly across the gang)."""
        if self.shuffle:
            rng = np.random.default_rng((self.seed, epoch))
            perm = rng.permutation(self.n)
        else:
            perm = np.arange(self.n)
        per = self.n // self.shard.count
        lo = self.shard.index * per
        return perm[lo:lo + per]

    def batches(self, epoch: int = 0) -> Iterator[dict[str, np.ndarray]]:
        idx = self.epoch_indices(epoch)
        n_full = len(idx) // self.batch_size
        end = n_full * self.batch_size if self.drop_remainder else len(idx)
        for lo in range(0, end, self.batch_size):
            sel = idx[lo:lo + self.batch_size]
            yield {k: v[sel] for k, v in self.arrays.items()}

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        """Endless stream: epoch 0, 1, 2, ... reshuffled each time."""
        epoch = 0
        while True:
            yield from self.batches(epoch)
            epoch += 1


def prefetch_to_device(it: Iterable, size: int = 2,
                       sharding=None) -> Iterator:
    """Keep ``size`` batches in flight on the device: each element is
    ``jax.device_put`` (with ``sharding`` when given) as soon as a slot
    frees, so the transfer of batch N+1 overlaps the compute consuming
    batch N.  jax transfers are async — device_put returns immediately
    and the queue depth is the buffer."""
    import jax

    def put(x):
        return jax.device_put(x, sharding) if sharding is not None \
            else jax.device_put(x)

    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    queue: collections.deque = collections.deque()
    it = iter(it)
    try:
        for _ in range(size):
            queue.append(jax.tree.map(put, next(it)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(jax.tree.map(put, next(it)))
        except StopIteration:
            pass
        yield out


def global_batches(it: Iterable, mesh, spec) -> Iterator:
    """Assemble each process-local batch into a GLOBAL jax.Array laid
    out by ``NamedSharding(mesh, spec)`` — multi-host dp: every process
    feeds only its own shard's rows and the global batch exists without
    any cross-host data movement (addressable shards only)."""
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    for batch in it:
        yield jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                sharding, np.asarray(x)),
            batch)


def synthetic_tokens(n: int, seq_len: int, vocab_size: int,
                     seed: int = 0) -> dict[str, np.ndarray]:
    """Deterministic synthetic causal-LM dataset ({'tokens': [n, T]}).

    All-T loss contract: the train step forwards the full [B, T] and
    computes next-token loss on T-1 positions internally — examples are
    exactly ``seq_len`` long so kernel block alignment survives."""
    rng = np.random.default_rng(seed)
    return {"tokens": rng.integers(
        0, vocab_size, (n, seq_len), dtype=np.int32)}


def synthetic_images(n: int, size: int, n_classes: int,
                     seed: int = 0) -> dict[str, np.ndarray]:
    """Deterministic synthetic image-classification dataset."""
    rng = np.random.default_rng(seed)
    return {
        "images": rng.standard_normal((n, size, size, 3),
                                      dtype=np.float32),
        "labels": rng.integers(0, n_classes, (n,), dtype=np.int32),
    }


def synthetic_features(n: int, dim: int, n_classes: int,
                       seed: int = 0) -> dict[str, np.ndarray]:
    """Deterministic flat-feature classification dataset
    ({'x': [n, dim] f32, 'y': [n] i32}) — the MLP workloads' source."""
    rng = np.random.default_rng(seed)
    return {"x": rng.standard_normal((n, dim), dtype=np.float32),
            "y": rng.integers(0, n_classes, (n,), dtype=np.int32)}

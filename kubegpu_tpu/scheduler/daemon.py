"""Standalone scheduler daemon: ``python -m kubegpu_tpu.scheduler.daemon``.

The reference's scheduler process (SURVEY.md §4.2): connect to the
apiserver over HTTP, maintain a watch-fed local cache (the client-go
reflector equivalent — ``kubemeta/cache.py``), and run the scheduling
loop event-driven against that cache.  Every read (``run_once``'s
pending scan, ``sync``'s full rebuild) is served locally; only
binds/patches cross the wire.  Restart recovery is the annotation-truth
path the scheduler already has: a fresh daemon's first ``sync()``
rebuilds every commitment from pod annotations (SURVEY.md §4.4).

(`scheduler/serve.py` is the kube-scheduler-facing extender WEBHOOK;
this module is the full device scheduler as its own control loop.)

    python -m kubegpu_tpu.scheduler.daemon \
        --apiserver http://127.0.0.1:8901
"""

from __future__ import annotations

import argparse
import sys
import threading
import time

from kubegpu_tpu.kubemeta.controlplane import Conflict, NotFound


def build_scheduler(args):
    """(api client, cache, scheduler, recovery) from flags — split from
    main() so tests can drive the daemon in-process."""
    from kubegpu_tpu.kubemeta.apiserver_http import HttpApiClient
    from kubegpu_tpu.kubemeta.cache import WatchCachedApiClient
    from kubegpu_tpu.obs import global_registry
    from kubegpu_tpu.scheduler.extender import DeviceScheduler
    from kubegpu_tpu.scheduler.health import FaultRecoveryController

    api = HttpApiClient(args.apiserver)
    cache = None
    try:
        cache = WatchCachedApiClient(api)
        sched = DeviceScheduler(cache, metrics=global_registry,
                                gang_grace_s=args.gang_grace)
        recovery = FaultRecoveryController(cache, sched)
    except BaseException:
        # seeding can fail while the apiserver is still booting; the
        # retry loop builds a fresh client, so close this one or every
        # failed attempt leaks a long-poll watch thread that haunts the
        # server forever once it's up
        if cache is not None:
            cache.close()
        api.close()
        raise
    return api, cache, sched, recovery


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="kubetpu-scheduler",
        description="device scheduler daemon over the HTTP apiserver "
        "(watch-cached reads, event-driven loop)")
    ap.add_argument("--apiserver", required=True,
                    help="HTTP apiserver URL (kubemeta.apiserver_http)")
    ap.add_argument("--tick", type=float, default=1.0,
                    help="max seconds between passes when no events "
                    "arrive (events wake the loop immediately)")
    ap.add_argument("--gang-grace", type=float, default=30.0,
                    help="incomplete-gang head-of-line grace (seconds)")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve Prometheus text at GET /metrics on this "
                    "port (0 = disabled) — the webhook's obs surface, "
                    "for the daemon deployment shape")
    ap.add_argument("--metrics-host", default="127.0.0.1",
                    help="bind address for /metrics (use 0.0.0.0 in a "
                    "container netns so an off-host scraper can reach it)")
    args = ap.parse_args(argv)

    backoff = 0.2
    while True:   # the apiserver may still be coming up (concurrent boot)
        try:
            api, cache, sched, recovery = build_scheduler(args)
            break
        except (OSError, ValueError, Conflict, NotFound) as e:
            print(f"scheduler: cannot reach {args.apiserver}, retrying "
                  f"in {backoff:.1f}s: {e}", file=sys.stderr)
            time.sleep(backoff)
            backoff = min(backoff * 2, 10.0)
    # One-time warm-up BEFORE declaring readiness: building/dlopening
    # the native allocator core + seeding the geometry memos otherwise
    # lands on the first real decision (r3 wire bench: 506 ms max vs
    # 4.5 ms p50).  Readiness means "first decision runs at steady
    # state".
    t_warm = time.perf_counter()
    sched.warm_start()
    print(f"scheduler: warmed in "
          f"{(time.perf_counter() - t_warm) * 1e3:.0f} ms", flush=True)

    # Event-driven wakeup: pod/node churn triggers an immediate pass
    # (the recovery controller watches through the same cache and marks
    # itself dirty on node events); completions release chips exactly
    # like SimCluster._on_event does in-process.
    wake = threading.Event()

    def on_event(ev) -> None:
        if ev.kind == "Pod":
            from kubegpu_tpu.kubemeta.objects import PodPhase
            pod = ev.obj
            if ev.type == "DELETED" or (
                    ev.type == "MODIFIED" and pod.status.phase in (
                        PodPhase.SUCCEEDED, PodPhase.FAILED)):
                try:
                    sched.return_pod_resources(pod.name,
                                               pod.metadata.namespace)
                except Exception as e:   # releasing must never kill us
                    print(f"scheduler: release error for {pod.name}: "
                          f"{e}", file=sys.stderr)
        wake.set()

    # Subscribe BEFORE declaring readiness: a client that reacts to the
    # readiness line by creating a Pod must find the wakeup path live —
    # the r3 wire bench's 506 ms max was exactly this race (the first
    # event slipped in before the watcher existed, so the first
    # decision waited out one full --tick; 500 ms tick + ~6 ms pass).
    unsub = cache.watch(on_event)
    print(f"scheduler: connected to {args.apiserver}", flush=True)

    metrics_srv = None
    if args.metrics_port:
        from kubegpu_tpu.obs.metrics import serve_prometheus
        metrics_srv = serve_prometheus(sched.metrics, args.metrics_host,
                                       args.metrics_port)
        print(f"scheduler: /metrics on port "
              f"{metrics_srv.server_address[1]}", flush=True)
    backoff = args.tick
    try:
        while True:
            wake.wait(timeout=args.tick)
            wake.clear()
            try:
                recovery.run_once()
                t_pass = time.perf_counter()
                res = sched.run_once()
                pass_ms = (time.perf_counter() - t_pass) * 1e3
                if pass_ms > 100.0:
                    # phase visibility for latency outliers (VERDICT r3
                    # weak #5): the pass time here is decision compute
                    # + bind POSTs; watch delivery is the client's side
                    print(f"scheduler: slow pass {pass_ms:.0f} ms "
                          f"(scheduled={len(res.scheduled)} "
                          f"unschedulable={len(res.unschedulable)})",
                          flush=True)
                backoff = args.tick
            except (OSError, ValueError, NotFound, Conflict) as e:
                # transient control-plane failure: back off, retry —
                # in-memory state re-syncs from annotation truth
                print(f"scheduler: control-plane error, retrying in "
                      f"{backoff:.1f}s: {e}", file=sys.stderr)
                time.sleep(backoff)
                backoff = min(backoff * 2, 10.0)
                try:
                    sched.sync()
                except Exception:
                    pass
    except KeyboardInterrupt:
        pass
    finally:
        unsub()
        recovery.close()
        cache.close()
        api.close()
        if metrics_srv is not None:
            metrics_srv.shutdown()
            metrics_srv.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

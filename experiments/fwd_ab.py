"""Interleaved A/B of forward flash-attention block geometry (r5).

Same protocol as dkv_ab.py: compile all variants on a quiet device,
then alternate timing bursts so tunnel weather cancels."""

import importlib
import statistics
import sys
import time

sys.path.insert(0, "/root/repo")

import jax                                      # noqa: E402
import jax.numpy as jnp                         # noqa: E402
import numpy as np                              # noqa: E402

fa = importlib.import_module("kubegpu_tpu.ops.flash_attention")

B, HQ, HKV, T, D = 4, 16, 4, 2048, 128
DT = jnp.bfloat16
ITERS = 100
ROUNDS = 5


def fetch(x):
    return float(np.asarray(jax.device_get(jnp.ravel(x)[0])))


def main():
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, HQ, T, D), DT)
    k = jax.random.normal(kk, (B, HKV, T, D), DT)
    v = jax.random.normal(kv, (B, HKV, T, D), DT)

    variants = {}
    for bq, bk in ((256, 512), (512, 512), (256, 1024), (512, 1024),
                   (128, 512), (256, 2048)):
        name = f"bq{bq}/bk{bk}"
        try:
            fn = jax.jit(lambda q_, bq=bq, bk=bk: fa.flash_attention(
                q_, k, v, block_q=bq, block_k=bk))
            fetch(fn(q))
            variants[name] = fn
            print(f"compiled {name}", flush=True)
        except Exception as e:
            print(f"{name}: COMPILE FAILED {str(e)[:120]}", flush=True)

    times = {n: [] for n in variants}
    for _ in range(ROUNDS):
        for name, fn in variants.items():
            st = q
            t0 = time.perf_counter()
            for _ in range(ITERS):
                st = fn(st)
            fetch(st)
            times[name].append((time.perf_counter() - t0) / ITERS)
    for name, ts in times.items():
        print(f"fwd {name}: median {statistics.median(ts)*1e3:7.3f} ms "
              f"(all: {[round(t*1e3, 3) for t in ts]})", flush=True)


if __name__ == "__main__":
    main()

"""KV-cache serving path: decode must agree with the full forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubegpu_tpu.models import (
    LlamaConfig, greedy_generate, llama_forward, llama_init, prefill,
)
from kubegpu_tpu.models.decode import decode_step


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(n_layers=3, n_heads=4, n_kv_heads=2,
                           max_seq_len=64)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestPrefillDecode:
    def test_prefill_matches_forward_last_logits(self, tiny):
        cfg, params = tiny
        prompt = (jnp.arange(2 * 9, dtype=jnp.int32).reshape(2, 9) * 7
                  ) % cfg.vocab_size
        ref = llama_forward(params, prompt, cfg)[:, -1]
        got, _ = jax.jit(lambda p, t: prefill(p, t, cfg))(params, prompt)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    def test_decode_steps_match_forward(self, tiny):
        """Feeding tokens one at a time through the cache must reproduce
        the full-sequence forward logits at every position."""
        cfg, params = tiny
        seq = (jnp.arange(12, dtype=jnp.int32)[None, :] * 5
               ) % cfg.vocab_size
        ref = llama_forward(params, seq, cfg)   # [1, 12, V]
        logits, cache = prefill(params, seq[:, :4], cfg)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(ref[:, 3]),
                                   atol=2e-4, rtol=2e-4)
        step = jax.jit(
            lambda p, c, tok, pos: decode_step(p, c, tok, pos, cfg))
        for pos in range(4, 12):
            logits, cache = step(params, cache, seq[:, pos], pos)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(ref[:, pos]),
                atol=3e-4, rtol=3e-4,
                err_msg=f"mismatch at position {pos}")

    def test_greedy_generate_matches_naive_rollout(self, tiny):
        """The scanned cache decode must pick the same tokens as the
        O(n^2) no-cache rollout."""
        cfg, params = tiny
        prompt = (jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5) * 3
                  ) % cfg.vocab_size
        n = 6
        got = greedy_generate(params, prompt, n, cfg)
        seq = prompt
        for _ in range(n):
            logits = llama_forward(params, seq, cfg)[:, -1]
            nxt = jnp.argmax(logits, axis=-1).astype(seq.dtype)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(seq[:, 5:]))

    def test_gqa_cache_shapes(self, tiny):
        cfg, params = tiny
        from kubegpu_tpu.models import init_kv_cache
        cache = init_kv_cache(cfg, batch=3, max_len=32)
        # [L, B, Hkv, S, D]
        assert cache["k"].shape == (3, 3, 2, 32, cfg.head_dim)
        assert cache["v"].shape == cache["k"].shape

    def test_overflow_rejected(self, tiny):
        cfg, params = tiny
        prompt = jnp.zeros((1, 60), jnp.int32)
        with pytest.raises(ValueError, match="max_len"):
            greedy_generate(params, prompt, 10, cfg)


class TestKvInt8:
    def test_prefill_logits_close_to_bf16_cache(self, tiny):
        """int8 cache with per-token scales: last-position logits must
        track the exact-cache path closely (8-bit symmetric round-off
        only)."""
        cfg, params = tiny
        prompt = (jnp.arange(2 * 9, dtype=jnp.int32).reshape(2, 9) * 7
                  ) % cfg.vocab_size
        ref, _ = prefill(params, prompt, cfg)
        got, cache = prefill(params, prompt, cfg, kv_int8=True)
        assert cache["k"].dtype == jnp.int8
        assert cache["k_scale"].shape == cache["k"].shape[:-1]
        err = np.max(np.abs(np.asarray(got) - np.asarray(ref)))
        ref_mag = np.max(np.abs(np.asarray(ref)))
        assert err < 0.02 * max(ref_mag, 1.0), (err, ref_mag)

    def test_decode_step_consumes_quantized_cache(self, tiny):
        cfg, params = tiny
        seq = (jnp.arange(12, dtype=jnp.int32)[None, :] * 5
               ) % cfg.vocab_size
        ref = llama_forward(params, seq, cfg)
        logits, cache = prefill(params, seq[:, :4], cfg, kv_int8=True)
        for pos in range(4, 8):
            logits, cache = decode_step(params, cache, seq[:, pos],
                                        pos, cfg)
            # loose: int8 cache round-off accumulates over positions
            err = np.max(np.abs(np.asarray(logits)
                                - np.asarray(ref[:, pos])))
            assert err < 0.05 * max(
                float(np.max(np.abs(np.asarray(ref[:, pos])))), 1.0)

    def test_greedy_generate_kv_int8_tokens_mostly_agree(self, tiny):
        """Token-level agreement with the exact cache on a tiny model —
        argmax can legitimately flip on near-ties, so require majority
        agreement, not identity."""
        cfg, params = tiny
        prompt = (jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5) * 3
                  ) % cfg.vocab_size
        exact = np.asarray(greedy_generate(params, prompt, 6, cfg))
        quant = np.asarray(greedy_generate(params, prompt, 6, cfg,
                                           kv_int8=True))
        assert (exact == quant).mean() >= 0.5, (exact, quant)


class TestSampling:
    def test_near_zero_temperature_matches_greedy(self, tiny):
        from kubegpu_tpu.models import sample_generate
        cfg, params = tiny
        prompt = (jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5) * 3
                  ) % cfg.vocab_size
        greedy = np.asarray(greedy_generate(params, prompt, 6, cfg))
        sampled = np.asarray(sample_generate(
            params, prompt, 6, cfg, jax.random.PRNGKey(0),
            temperature=1e-5))
        np.testing.assert_array_equal(sampled, greedy)

    def test_top_k_one_matches_greedy(self, tiny):
        from kubegpu_tpu.models import sample_generate
        cfg, params = tiny
        prompt = (jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5) * 3
                  ) % cfg.vocab_size
        greedy = np.asarray(greedy_generate(params, prompt, 6, cfg))
        sampled = np.asarray(sample_generate(
            params, prompt, 6, cfg, jax.random.PRNGKey(7), top_k=1,
            temperature=5.0))   # high temp: only the k-mask saves us
        np.testing.assert_array_equal(sampled, greedy)

    def test_deterministic_per_key_and_varies_across_keys(self, tiny):
        from kubegpu_tpu.models import sample_generate
        cfg, params = tiny
        prompt = (jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5) * 3
                  ) % cfg.vocab_size
        a1 = np.asarray(sample_generate(
            params, prompt, 8, cfg, jax.random.PRNGKey(1),
            temperature=2.0))
        a2 = np.asarray(sample_generate(
            params, prompt, 8, cfg, jax.random.PRNGKey(1),
            temperature=2.0))
        b = np.asarray(sample_generate(
            params, prompt, 8, cfg, jax.random.PRNGKey(2),
            temperature=2.0))
        np.testing.assert_array_equal(a1, a2)
        assert (a1 != b).any()   # hot sampling: keys must matter
        assert (a1 >= 0).all() and (a1 < cfg.vocab_size).all()

    def test_top_p_restricts_support(self, tiny):
        """With a sharply peaked distribution (tiny top_p) sampling must
        collapse to the argmax even at high temperature."""
        from kubegpu_tpu.models import sample_generate
        cfg, params = tiny
        prompt = (jnp.arange(5, dtype=jnp.int32)[None] * 3
                  ) % cfg.vocab_size
        greedy = np.asarray(greedy_generate(params, prompt, 4, cfg))
        for seed in range(3):
            got = np.asarray(sample_generate(
                params, prompt, 4, cfg, jax.random.PRNGKey(seed),
                temperature=1.0, top_p=1e-6))
            np.testing.assert_array_equal(got, greedy)

    def test_kv_int8_sampling_runs(self, tiny):
        from kubegpu_tpu.models import sample_generate
        cfg, params = tiny
        prompt = (jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5)
                  ) % cfg.vocab_size
        out = np.asarray(sample_generate(
            params, prompt, 4, cfg, jax.random.PRNGKey(3),
            temperature=0.8, top_k=8, top_p=0.9, kv_int8=True))
        assert out.shape == (2, 4)
        assert (out >= 0).all() and (out < cfg.vocab_size).all()

    def test_degenerate_knobs_rejected(self, tiny):
        from kubegpu_tpu.models import sample_generate
        cfg, params = tiny
        prompt = jnp.zeros((1, 4), jnp.int32)
        with pytest.raises(ValueError, match="top_p"):
            sample_generate(params, prompt, 2, cfg,
                            jax.random.PRNGKey(0), top_p=0.0)
        with pytest.raises(ValueError, match="temperature"):
            sample_generate(params, prompt, 2, cfg,
                            jax.random.PRNGKey(0), temperature=0.0)
        with pytest.raises(ValueError, match="top_k"):
            sample_generate(params, prompt, 2, cfg,
                            jax.random.PRNGKey(0), top_k=-1)


class TestBeamSearch:
    def _seq_logprob(self, params, cfg, prompt, gen):
        """Teacher-forced sum of logprobs of `gen` after `prompt` —
        independent ground truth for the beam's score bookkeeping."""
        full = jnp.concatenate([prompt, gen], axis=1)
        logits = llama_forward(params, full[:, :-1], cfg)
        logp = jax.nn.log_softmax(logits, axis=-1)
        t = prompt.shape[1]
        picked = jnp.take_along_axis(
            logp[:, t - 1:], gen[..., None], axis=-1)[..., 0]
        return np.asarray(picked.sum(axis=1))

    def test_beam_one_equals_greedy(self, tiny):
        from kubegpu_tpu.models.decode import beam_generate
        cfg, params = tiny
        prompt = (jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5) * 3
                  ) % cfg.vocab_size
        greedy = np.asarray(greedy_generate(params, prompt, 5, cfg))
        toks, score = beam_generate(params, prompt, 5, cfg, beams=1)
        np.testing.assert_array_equal(np.asarray(toks), greedy)
        want = self._seq_logprob(params, cfg, prompt, jnp.asarray(greedy))
        np.testing.assert_allclose(np.asarray(score), want,
                                   atol=2e-3, rtol=2e-3)

    def test_beam_score_matches_teacher_forcing(self, tiny):
        """The returned score must equal the independently recomputed sum-logprob
        of the returned tokens — catches any cache-gather or position
        bookkeeping bug."""
        from kubegpu_tpu.models.decode import beam_generate
        cfg, params = tiny
        prompt = (jnp.arange(2 * 6, dtype=jnp.int32).reshape(2, 6) * 7
                  ) % cfg.vocab_size
        toks, score = beam_generate(params, prompt, 4, cfg, beams=4)
        want = self._seq_logprob(params, cfg, prompt, toks)
        np.testing.assert_allclose(np.asarray(score), want,
                                   atol=2e-3, rtol=2e-3)

    def test_single_step_beam_is_exact(self, tiny):
        """For n_steps=1 beam search IS exhaustive over the first
        token, so width-W's best must equal the true argmax path —
        a guaranteed optimality property (final-score monotonicity in
        W for longer rollouts is NOT one, and is deliberately not
        asserted)."""
        from kubegpu_tpu.models.decode import beam_generate
        cfg, params = tiny
        prompt = (jnp.arange(5, dtype=jnp.int32)[None] * 11
                  ) % cfg.vocab_size
        greedy = np.asarray(greedy_generate(params, prompt, 1, cfg))
        for w in (1, 4):
            toks, score = beam_generate(params, prompt, 1, cfg, beams=w)
            np.testing.assert_array_equal(np.asarray(toks), greedy)
            want = self._seq_logprob(params, cfg, prompt,
                                     jnp.asarray(greedy))
            np.testing.assert_allclose(np.asarray(score), want,
                                       atol=2e-3, rtol=2e-3)

    def test_beam_with_kv_int8(self, tiny):
        from kubegpu_tpu.models.decode import beam_generate
        cfg, params = tiny
        prompt = (jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5)
                  ) % cfg.vocab_size
        toks, score = beam_generate(params, prompt, 3, cfg, beams=3,
                                    kv_int8=True)
        assert toks.shape == (2, 3)
        assert np.isfinite(np.asarray(score)).all()

    def test_beam_validation(self, tiny):
        from kubegpu_tpu.models.decode import beam_generate
        cfg, params = tiny
        prompt = jnp.zeros((1, 4), jnp.int32)
        with pytest.raises(ValueError, match="beams"):
            beam_generate(params, prompt, 2, cfg, beams=0)


class TestSpeculative:
    def test_output_identical_to_greedy(self, tiny):
        """THE speculative-decoding contract: the draft decides how many
        tokens each full forward yields, never which."""
        from kubegpu_tpu.models.decode import spec_generate
        cfg, params = tiny
        prompt = (jnp.arange(2 * 6, dtype=jnp.int32).reshape(2, 6) * 7
                  ) % cfg.vocab_size
        for n in (1, 2, 9):
            greedy = np.asarray(greedy_generate(params, prompt, n, cfg))
            for dl, g in ((1, 4), (2, 2), (3, 3)):
                toks, _ = spec_generate(params, prompt, n, cfg,
                                        draft_layers=dl, gamma=g)
                np.testing.assert_array_equal(
                    np.asarray(toks), greedy,
                    err_msg=f"n={n} draft_layers={dl} gamma={g}")

    def test_perfect_draft_accepts_everything(self, tiny):
        """draft_layers == n_layers: the draft IS the model, so every
        proposal matches and acceptance saturates at (gamma-1)/gamma
        (the g-th token is emitted as the correction by design)."""
        from kubegpu_tpu.models.decode import spec_generate
        cfg, params = tiny
        prompt = (jnp.arange(5, dtype=jnp.int32)[None] * 3
                  ) % cfg.vocab_size
        toks, stats = spec_generate(params, prompt, 12, cfg,
                                    draft_layers=cfg.n_layers, gamma=4)
        greedy = np.asarray(greedy_generate(params, prompt, 12, cfg))
        np.testing.assert_array_equal(np.asarray(toks), greedy)
        # every iteration advances by gamma tokens (g-1 accepted + 1)
        assert stats["iterations"] <= -(-12 // 4) + 1
        assert stats["acceptance_rate"] >= 0.6

    def test_kv_int8_and_stats(self, tiny):
        from kubegpu_tpu.models.decode import spec_generate
        cfg, params = tiny
        prompt = (jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5)
                  ) % cfg.vocab_size
        toks, stats = spec_generate(params, prompt, 6, cfg,
                                    draft_layers=1, gamma=3,
                                    kv_int8=True)
        assert toks.shape == (2, 6)
        assert 0.0 <= stats["acceptance_rate"] <= 1.0
        assert stats["iterations"] >= 1

    def test_validation(self, tiny):
        from kubegpu_tpu.models.decode import spec_generate
        cfg, params = tiny
        prompt = jnp.zeros((1, 4), jnp.int32)
        with pytest.raises(ValueError, match="draft_layers"):
            spec_generate(params, prompt, 2, cfg, draft_layers=0)
        with pytest.raises(ValueError, match="gamma"):
            spec_generate(params, prompt, 2, cfg, draft_layers=1,
                          gamma=0)

    def test_fused_matches_host_loop(self, tiny):
        """spec_generate_fused (one lax.while_loop executable) must emit
        exactly the host loop's tokens — which are exactly greedy's —
        for every (draft, gamma) shape, including n_steps that end
        mid-slab."""
        from kubegpu_tpu.models.decode import (
            spec_generate,
            spec_generate_fused,
        )
        cfg, params = tiny
        prompt = (jnp.arange(2 * 6, dtype=jnp.int32).reshape(2, 6) * 5
                  ) % cfg.vocab_size
        for n in (1, 2, 9):
            greedy = np.asarray(greedy_generate(params, prompt, n, cfg))
            for dl, g in ((1, 4), (2, 2), (3, 3)):
                host, hstats = spec_generate(params, prompt, n, cfg,
                                             draft_layers=dl, gamma=g)
                fused, fstats = spec_generate_fused(
                    params, prompt, n, cfg, draft_layers=dl, gamma=g)
                np.testing.assert_array_equal(
                    np.asarray(fused), greedy,
                    err_msg=f"n={n} draft_layers={dl} gamma={g}")
                np.testing.assert_array_equal(np.asarray(host), greedy)
                # n=1: the prefill emits the only token, the loop never
                # runs — zero iterations is the correct report
                assert fstats["iterations"] >= (1 if n > 1 else 0)
                assert 0.0 <= fstats["acceptance_rate"] <= 1.0

    def test_fused_kv_int8(self, tiny):
        from kubegpu_tpu.models.decode import spec_generate_fused
        cfg, params = tiny
        prompt = (jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5)
                  ) % cfg.vocab_size
        toks, stats = spec_generate_fused(params, prompt, 6, cfg,
                                          draft_layers=1, gamma=3,
                                          kv_int8=True)
        greedy = np.asarray(greedy_generate(params, prompt, 6, cfg,
                                            kv_int8=True))
        np.testing.assert_array_equal(np.asarray(toks), greedy)

    def test_perfect_draft_fused_acceptance(self, tiny):
        """draft == model: the fused loop's acceptance must saturate at
        1.0 now that the denominator counts acceptable slots (γ-1), not
        proposals (the r2 advisor finding)."""
        from kubegpu_tpu.models.decode import spec_generate_fused
        cfg, params = tiny
        prompt = (jnp.arange(5, dtype=jnp.int32)[None] * 3
                  ) % cfg.vocab_size
        # n=12 truncates the final slab (11 = 3+3+3+2): the proposed
        # counter must mirror the host loop's min(gamma, remaining) - 1
        # so a perfect draft still reads 1.0 (r3 review finding — the
        # fixed-gamma denominator under-reported exactly these shapes)
        toks, stats = spec_generate_fused(params, prompt, 12, cfg,
                                          draft_layers=cfg.n_layers,
                                          gamma=4)
        greedy = np.asarray(greedy_generate(params, prompt, 12, cfg))
        np.testing.assert_array_equal(np.asarray(toks), greedy)
        assert stats["acceptance_rate"] == 1.0

    def test_quantized_params_supported(self, tiny):
        """int8 weight trees (QTensor leaves) must slice into the draft
        view and decode — the quant.py drop-in contract extends to
        speculative decoding."""
        from kubegpu_tpu.models.decode import draft_view, spec_generate
        from kubegpu_tpu.models.quant import quantize_llama
        cfg, params = tiny
        qparams = quantize_llama(params)
        dview = draft_view(qparams, 2)
        assert dview["layers"]["wq"].values.shape[0] == 2
        prompt = (jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5)
                  ) % cfg.vocab_size
        toks, _ = spec_generate(qparams, prompt, 4, cfg,
                                draft_layers=2, gamma=2,
                                dparams=dview)
        greedy = np.asarray(greedy_generate(qparams, prompt, 4, cfg))
        np.testing.assert_array_equal(np.asarray(toks), greedy)


class TestPromptLookup:
    """Prompt-lookup (n-gram) speculative decoding: draft-model-free,
    bit-exact with greedy in f32 regardless of acceptance."""

    def test_exact_on_repetitive_prompt(self, tiny):
        cfg, params = tiny
        import numpy as np

        from kubegpu_tpu.models.decode import pld_generate_fused
        pat = np.asarray([3, 7, 11, 5, 2, 9, 4, 8])
        prompt = jnp.asarray(np.tile(pat, 4)[None].repeat(2, 0),
                             jnp.int32)
        g = greedy_generate(params, prompt, 24, cfg, max_len=128)
        p, stats = pld_generate_fused(params, prompt, 24, cfg,
                                      gamma=6, ngram=3, max_len=128)
        assert (np.asarray(g) == np.asarray(p)).all()
        assert stats["iterations"] >= 1
        assert 0.0 <= stats["acceptance_rate"] <= 1.0

    def test_exact_on_nonrepetitive_prompt(self, tiny):
        cfg, params = tiny
        import numpy as np

        from kubegpu_tpu.models.decode import pld_generate_fused
        prompt = jnp.asarray(
            (np.arange(40)[None].repeat(2, 0) * 37 + 11)
            % cfg.vocab_size, jnp.int32)
        g = greedy_generate(params, prompt, 12, cfg, max_len=128)
        p, stats = pld_generate_fused(params, prompt, 12, cfg,
                                      gamma=4, ngram=3, max_len=128)
        assert (np.asarray(g) == np.asarray(p)).all()

    def test_validation(self, tiny):
        cfg, params = tiny
        import pytest as _pytest

        from kubegpu_tpu.models.decode import pld_generate_fused
        prompt = jnp.zeros((1, 8), jnp.int32)
        with _pytest.raises(ValueError, match="gamma"):
            pld_generate_fused(params, prompt, 4, cfg, gamma=0)
        with _pytest.raises(ValueError, match="ngram"):
            pld_generate_fused(params, prompt, 4, cfg, ngram=0)


class TestBeamOnPages:
    """beam_generate_paged: the prompt segment lives in a page pool
    read by the paged-attention kernel, with every beam of a sequence
    aliasing the same pages (VERDICT r4 weak #6 — beam search joins
    the paged KV regime).  Parity against the dense two-segment
    implementation is exact at f32."""

    def test_matches_dense_beam(self):
        import jax

        from kubegpu_tpu.models import (
            LlamaConfig, beam_generate, beam_generate_paged, llama_init,
        )
        cfg = LlamaConfig.tiny(max_seq_len=64, n_heads=4, n_kv_heads=2)
        params = llama_init(jax.random.PRNGKey(3), cfg)
        prompt = jnp.asarray(
            np.arange(2 * 11).reshape(2, 11) % cfg.vocab_size, jnp.int32)
        toks_d, scores_d = beam_generate(params, prompt, 7, cfg, beams=3)
        toks_p, scores_p = beam_generate_paged(params, prompt, 7, cfg,
                                               beams=3, page_size=8)
        np.testing.assert_array_equal(np.asarray(toks_d),
                                      np.asarray(toks_p))
        np.testing.assert_allclose(np.asarray(scores_d),
                                   np.asarray(scores_p), atol=1e-4)

    def test_unaligned_prompt_pads_into_pages(self):
        """A prompt that doesn't fill its last page must mask the pad
        region (validity phys < t), not attend garbage."""
        import jax

        from kubegpu_tpu.models import (
            LlamaConfig, beam_generate, beam_generate_paged, llama_init,
        )
        cfg = LlamaConfig.tiny(max_seq_len=64, n_heads=4, n_kv_heads=4)
        params = llama_init(jax.random.PRNGKey(4), cfg)
        prompt = jnp.asarray(
            (np.arange(3 * 5).reshape(3, 5) * 7) % cfg.vocab_size,
            jnp.int32)   # 5 tokens, page_size 8 → one partial page
        toks_d, _ = beam_generate(params, prompt, 6, cfg, beams=2)
        toks_p, _ = beam_generate_paged(params, prompt, 6, cfg,
                                        beams=2, page_size=8)
        np.testing.assert_array_equal(np.asarray(toks_d),
                                      np.asarray(toks_p))


class TestPLDOnPages:
    """pld_generate_paged: the speculative verify forward reads its KV
    history from a page pool (chunk queries folded into the paged
    kernel's group dim; chunk K/V written into a 2-page window, with
    rejected entries masked by the next iteration's validity scalar).
    Exact parity with the dense fused implementation at f32."""

    def test_matches_dense_pld(self):
        import jax

        from kubegpu_tpu.models import LlamaConfig, llama_init
        from kubegpu_tpu.models.decode import (
            pld_generate_fused,
            pld_generate_paged,
        )
        cfg = LlamaConfig.tiny(max_seq_len=96, n_heads=4, n_kv_heads=2)
        params = llama_init(jax.random.PRNGKey(9), cfg)
        # a repeating prompt so the lookup actually accepts drafts
        pat = np.asarray([5, 9, 2, 7])
        prompt = jnp.asarray(
            np.tile(pat, 5)[None].repeat(2, 0), jnp.int32)   # [2, 20]
        dense, ds = pld_generate_fused(params, prompt, 14, cfg,
                                       gamma=4, ngram=2, max_len=48)
        paged, ps = pld_generate_paged(params, prompt, 14, cfg,
                                       gamma=4, ngram=2, max_len=48,
                                       page_size=8)
        np.testing.assert_array_equal(np.asarray(dense),
                                      np.asarray(paged))
        assert ds["acceptance_rate"] == ps["acceptance_rate"]
        assert ds["iterations"] == ps["iterations"]
        # drafts were really accepted (the paged path exercised
        # multi-token takes, not just greedy fallback)
        assert ps["acceptance_rate"] > 0

    def test_nonrepeating_prompt_still_exact(self):
        import jax

        from kubegpu_tpu.models import LlamaConfig, llama_init
        from kubegpu_tpu.models.decode import (
            pld_generate_fused,
            pld_generate_paged,
        )
        cfg = LlamaConfig.tiny(max_seq_len=64, n_heads=4, n_kv_heads=4)
        params = llama_init(jax.random.PRNGKey(10), cfg)
        prompt = jnp.asarray(
            (np.arange(2 * 9).reshape(2, 9) * 11) % cfg.vocab_size,
            jnp.int32)
        dense, _ = pld_generate_fused(params, prompt, 8, cfg,
                                      gamma=3, ngram=2, max_len=32)
        paged, _ = pld_generate_paged(params, prompt, 8, cfg,
                                      gamma=3, ngram=2, max_len=32,
                                      page_size=8)
        np.testing.assert_array_equal(np.asarray(dense),
                                      np.asarray(paged))

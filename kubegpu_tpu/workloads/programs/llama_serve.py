"""Llama serving workload — decode as a SCHEDULABLE job, not just a
library call: the pod runs prefill + greedy decode on its allocated
chip(s) and prints metric lines the node agent harvests into the
cluster registry (like the allreduce bench does for north-star #2).

Model scale is ANNOTATION-DRIVEN: when the allocation advertises a
whole chip's HBM (KUBETPU_HBM_GIB >= 16, crishim-injected from the
chip advertisement) and the backend is a real TPU, the pod serves the
flagship bench config (618M, int8 weights + int8 KV cache — the
>= 10k tok/s configuration from BASELINE.md) instead of the CPU-scale
tiny model.  SERVE_CONFIG overrides: auto | tiny | bench.

Env knobs:
  SERVE_CONFIG   auto (default) | tiny | bench
  SERVE_MODE     static (default) | continuous — continuous runs the
                 arrival-driven ContinuousBatcher (models/serve.py):
                 SERVE_BATCH slots, SERVE_REQS sustained requests of
                 SERVE_STEPS tokens each, reporting steady-state
                 engine tok/s + occupancy
  SERVE_BATCH    sequences/slots (default 4 tiny / 32 bench)
  SERVE_PROMPT   prompt length (default 128 tiny / 1024 bench)
  SERVE_STEPS    decode steps per sequence (default 32 tiny / 128 bench)
  SERVE_REQS     continuous mode: total requests (default 3x slots)
  SERVE_INT8     "1" quantizes weights AND KV cache
                 (default: 0 tiny, 1 bench; continuous mode uses int8
                 weights only — its cache is bf16)
  SERVE_SPEC_GAMMA  continuous+paged: engine-integrated speculative
                 decoding — γ early-exit self-draft proposals per slot
                 per tick, one full-model verify (0 = off, greedy
                 only); SERVE_DRAFT_LAYERS picks the draft slice
                 (default n_layers/4).  The pod echoes
                 serve_engine_spec_accept_rate and
                 serve_engine_spec_tokens_per_tick so the harvested
                 tok/s carries the acceptance that produced it
  SERVE_FUSED_K  continuous+paged: fused multi-tick decode — run K
                 complete engine ticks per host round-trip (default 1;
                 the engine drops any block back to K=1 while host
                 work is pending: admission waves, prefill chunks,
                 quarantine replays).  Paged-only; under strict mode a
                 fused ask on the dense fallback aborts.  The pod
                 echoes serve_engine_cfg_fused_k and
                 serve_fused_dispatches
  SERVE_KV_BITS  continuous+paged: KV-pool element width — 16 (bf16),
                 8 (per-token int8, alias of SERVE_KV_INT8=1) or 4
                 (grouped packed int4, ISSUE 15).  The pod echoes
                 serve_kv_bits
  SERVE_EVICT_POLICY  continuous+paged: attention-aware page eviction
                 — "window" (drop prompt pages wholly outside the
                 trailing token window) or "mass" (drop low-attention-
                 mass prompt pages); SERVE_EVICT_PARAM tunes the
                 window length / mass threshold.  Plain-K=1-path only
                 (no spec/fused/mesh); the pod echoes
                 serve_pages_evicted_total and serve_kv_quality_delta

The decode throughput metric subtracts a separately-timed prefill of
the same configuration (the advisor's r2 finding: dividing by an
elapsed that includes prefill under-reports decode and diverges from
benchmark.py's methodology); the prefill-inclusive figure is emitted
separately as serve_e2e_tokens_per_s.
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> int:
    from kubegpu_tpu.workloads.programs.distributed import init_from_env

    env = init_from_env()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubegpu_tpu.models import (
        LlamaConfig, greedy_generate, llama_init, quantize_llama,
    )
    from kubegpu_tpu.models.decode import prefill

    mode = os.environ.get("SERVE_CONFIG", "auto")
    on_tpu = jax.devices()[0].platform.startswith(("tpu", "axon"))
    if mode == "auto":
        mode = ("bench" if on_tpu and (env.hbm_gib or 0.0) >= 16.0
                else "tiny")

    if mode == "bench":
        from kubegpu_tpu.benchmark import llama_bench_config
        batch = int(os.environ.get("SERVE_BATCH", "32"))
        prompt_t = int(os.environ.get("SERVE_PROMPT", "1024"))
        steps = int(os.environ.get("SERVE_STEPS", "128"))
        int8 = os.environ.get("SERVE_INT8", "1") == "1"
        cfg = llama_bench_config()
    else:
        batch = int(os.environ.get("SERVE_BATCH", "4"))
        prompt_t = int(os.environ.get("SERVE_PROMPT", "128"))
        steps = int(os.environ.get("SERVE_STEPS", "32"))
        int8 = os.environ.get("SERVE_INT8", "0") == "1"
        cfg = LlamaConfig.tiny(n_heads=4, n_kv_heads=4, dtype="float32",
                               max_seq_len=prompt_t + steps)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    if int8:
        params = quantize_llama(params)
    if os.environ.get("SERVE_MODE", "static") == "continuous":
        return _serve_continuous(env, cfg, params, batch, prompt_t,
                                 steps, int8)
    max_len = prompt_t + steps
    prompt = jnp.asarray(
        np.arange(batch * prompt_t).reshape(batch, prompt_t)
        % cfg.vocab_size, jnp.int32)

    def fetch(x):
        # host fetch = the only reliable barrier under the async tunnel
        return np.asarray(jax.device_get(jnp.ravel(x)[0]))

    def timeit(fn, n=2):
        out = fn()
        fetch(out)          # warm + compile
        t0 = time.perf_counter()
        fetch(out)
        rtt = time.perf_counter() - t0   # subtracted per burst: the
        # end fetch's network round trip is not model time (matching
        # benchmark.py's protocol — without this, e2e under-reports
        # by ~RTT/2 per burst under the tunnel)
        best = float("inf")
        for _ in range(2):  # best-of-2: tunnel noise only ever adds
            t0 = time.perf_counter()
            for _ in range(n):
                out = fn()
            fetch(out)
            best = min(best, max(time.perf_counter() - t0 - rtt, 1e-9))
        return best / n, out

    pf = jax.jit(lambda p, tk: prefill(p, tk, cfg, max_len,
                                       kv_int8=int8)[0])
    prefill_s, _ = timeit(lambda: pf(params, prompt))
    gen_s, out = timeit(
        lambda: greedy_generate(params, prompt, steps, cfg,
                                max_len=max_len, kv_int8=int8))
    decode_s = max(gen_s - prefill_s, 1e-9)
    first = int(np.asarray(out)[0, 0])

    ok = 0 <= first < cfg.vocab_size
    if env.worker_id == 0:
        common = {
            "unit": "tokens/s", "config": mode, "batch": batch,
            "prompt": prompt_t, "steps": steps, "int8": int8,
            "devices": jax.device_count(),
        }
        # the metric-line convention harvest_workload_metrics consumes;
        # decode is isolated against the same-config prefill, matching
        # benchmark.py's _serving_bench methodology
        print(json.dumps({
            "metric": "serve_decode_tokens_per_s",
            "value": round(batch * (steps - 1) / decode_s, 1),
            **common,
        }))
        print(json.dumps({
            "metric": "serve_e2e_tokens_per_s",
            "value": round(batch * steps / gen_s, 1),
            **common,
        }))
        # engine-config echo + per-phase timings, harvested into the
        # cluster registry so the scheduled-pod number can be
        # attributed line-by-line against the library bench run in the
        # same window (VERDICT r5 next-item #3: the ~23% pod tax was
        # unexplained because nothing committed said what the pod
        # actually ran or where its time went)
        for name, value in (
                ("serve_cfg_batch", batch),
                ("serve_cfg_prompt", prompt_t),
                ("serve_cfg_steps", steps),
                ("serve_cfg_int8", int(int8)),
                ("serve_phase_prefill_ms", round(prefill_s * 1e3, 2)),
                ("serve_phase_decode_ms", round(decode_s * 1e3, 2)),
                ("serve_phase_e2e_ms", round(gen_s * 1e3, 2))):
            print(json.dumps({"metric": name, "value": value}))
    if not ok:
        print("FAIL: generated token out of range", file=sys.stderr)
        return 3
    return 0


def _serve_continuous(env, cfg, params, n_slots, prompt_t, steps,
                      int8) -> int:
    """Arrival-driven serving as a schedulable workload: saturate a
    ContinuousBatcher with SERVE_REQS requests and report steady-state
    engine throughput + occupancy as harvestable metric lines."""
    import jax
    import numpy as np

    from kubegpu_tpu.models.serve import ContinuousBatcher

    stride = max(4, min(16, steps))
    n_reqs = int(os.environ.get("SERVE_REQS", str(3 * n_slots)))
    max_len = prompt_t + steps + stride + 8
    base = np.arange(prompt_t) % cfg.vocab_size
    # paged pool (r4 default for serving): the pallas paged-attention
    # engine measured faster than the dense slot cache AND the static
    # formulation on-chip, and KV HBM follows actual tokens held, not
    # n_slots x max_len.  Falls back to dense when the prompt bucket
    # doesn't align to a page (tiny smoke configs).
    page_size = 128
    paged = prompt_t % page_size == 0 and page_size % stride == 0
    if not paged:
        # strict mode (KUBETPU_REQUIRE_PALLAS=1) forbids this silent
        # paged→dense degradation: a bench/flagship run must abort
        # rather than attribute dense-engine throughput to the pool
        from kubegpu_tpu.ops.strict import fallback
        fallback("llama_serve.continuous",
                 f"prompt bucket {prompt_t} / stride {stride} does not "
                 f"align to page_size {page_size}; dense engine would "
                 "serve instead of the paged pool")
    # int8 KV pages only at the scale where the cache out-reads the
    # weights: r4 in-window A/B measured 1.11x at 32 slots x 1024
    # prompt but 0.80x at 8 x 512 (quantize-at-flush + in-kernel casts
    # outweigh the byte savings on small caches)
    kv_int8 = paged and n_slots * prompt_t >= 16384
    if os.environ.get("SERVE_KV_INT8") is not None:
        kv_int8 = paged and os.environ["SERVE_KV_INT8"] == "1"
    # kv bit-width (ISSUE 15): SERVE_KV_BITS=4 serves the grouped
    # packed-int4 pool (two channels per byte + per-group f32 scales);
    # =8 is an alias of SERVE_KV_INT8=1.  Paged-only — under strict
    # mode an int4 ask on the dense fallback aborts.
    kv_bits = None
    kb_env = os.environ.get("SERVE_KV_BITS")
    if kb_env:
        kv_bits = int(kb_env)
        if kv_bits == 4 and not paged:
            from kubegpu_tpu.ops.strict import fallback
            fallback("llama_serve.kv_bits",
                     "SERVE_KV_BITS=4 needs the paged engine; the "
                     "dense fallback has no packed page pool")
            kv_bits = None
        elif kv_bits == 8:
            kv_int8, kv_bits = paged, None
        elif kv_bits == 16:
            kv_int8, kv_bits = False, None
        if kv_bits == 4:
            kv_int8 = False
    # serving fast-path knobs (prefix caching + chunked prefill ride
    # the paged pool; defaults off so the harvested figure stays
    # comparable round-over-round unless explicitly enabled)
    prefix_cache = paged and os.environ.get(
        "SERVE_PREFIX_CACHE", "0") == "1"
    chunked = paged and os.environ.get(
        "SERVE_CHUNKED_PREFILL", "0") == "1"
    # engine-integrated speculative decoding (SERVE_SPEC_GAMMA > 0):
    # batched greedy early-exit self-draft + one full-model verify per
    # tick; SERVE_DRAFT_LAYERS picks the slice depth (default L/4).
    # Paged-only — under strict mode a spec ask on a dense fallback
    # aborts rather than silently serving the one-token path.
    spec_gamma = int(os.environ.get("SERVE_SPEC_GAMMA", "0"))
    dl_env = os.environ.get("SERVE_DRAFT_LAYERS")
    draft_layers = int(dl_env) if dl_env else None
    if spec_gamma and not paged:
        from kubegpu_tpu.ops.strict import fallback
        fallback("llama_serve.spec",
                 f"SERVE_SPEC_GAMMA={spec_gamma} needs the paged "
                 "engine; the dense fallback would serve the plain "
                 "one-token-per-slot path")
        spec_gamma = 0
    # fused multi-tick decode (SERVE_FUSED_K > 1): run K complete
    # engine ticks per host round-trip (ISSUE 8).  Paged-only — the
    # engine itself drops any block to K=1 whenever host work (an
    # admission wave, a prefill chunk, a quarantine replay) is
    # pending, so the knob is a ceiling, not a promise.
    fused_k = int(os.environ.get("SERVE_FUSED_K", "1"))
    if fused_k > 1 and not paged:
        from kubegpu_tpu.ops.strict import fallback
        fallback("llama_serve.fused",
                 f"SERVE_FUSED_K={fused_k} needs the paged engine; "
                 "the dense fallback syncs every tick")
        fused_k = 1
    # attention-aware page eviction (ISSUE 15): rides the plain K=1
    # decode path only — the mass signal comes out of the unfused
    # decode block, and a mesh-sharded pool's mass is a per-shard
    # statistic.  An incompatible ask degrades loudly, not silently.
    evict_policy = os.environ.get("SERVE_EVICT_POLICY") or None
    ep_env = os.environ.get("SERVE_EVICT_PARAM")
    evict_param = float(ep_env) if ep_env else None
    if evict_policy and (not paged or spec_gamma or fused_k > 1
                         or int(os.environ.get("SERVE_TP", "1")) > 1):
        from kubegpu_tpu.ops.strict import fallback
        fallback("llama_serve.evict",
                 f"SERVE_EVICT_POLICY={evict_policy} needs the paged "
                 "plain-decode engine (no spec/fused/tp); eviction "
                 "would silently stay off")
        evict_policy = evict_param = None
    # mesh-native serving (SERVE_TP / SERVE_DP): shard the paged engine
    # over tp chips (per-chip pools hold Hkv/tp heads) and/or run dp
    # independent replicas behind one admission queue.  Degrades to
    # the single-chip engine — loudly under strict mode — when the
    # allocation or the head geometry can't satisfy the ask.
    tp = int(os.environ.get("SERVE_TP", "1"))
    dp = int(os.environ.get("SERVE_DP", "1"))
    if paged and (tp > 1 or dp > 1):
        n_dev = jax.device_count()
        bad = []
        if tp * dp > n_dev:
            bad.append(f"dp*tp={dp * tp} > {n_dev} devices")
        if cfg.n_kv_heads % tp:
            bad.append(f"tp={tp} !| n_kv_heads={cfg.n_kv_heads}")
        if bad:
            from kubegpu_tpu.ops.strict import fallback
            fallback("llama_serve.tp",
                     "; ".join(bad) + " — single-chip engine would "
                     "serve instead of the mesh-sharded one")
            tp = dp = 1
    # end-to-end request tracing (ISSUE 6): the crishim injects
    # KUBETPU_TRACE_CONTEXT into this pod's env at create_container —
    # the same road TPU_VISIBLE_CHIPS travels.  Decoding it parents
    # every engine span (ticks, admissions, TTFT) under the
    # scheduler's bind span, one trace per request end to end.  No
    # token (or SERVE_TRACE=1 for a local root) → tracing stays off
    # and the engine runs the untraced fast path.
    from kubegpu_tpu.obs.spans import TRACE_ENV, SpanContext, Tracer
    trace_ctx = SpanContext.decode(os.environ.get(TRACE_ENV))
    tracer = (Tracer() if trace_ctx is not None
              or os.environ.get("SERVE_TRACE") == "1" else None)
    eng_kw = dict(n_slots=n_slots, max_len=max_len, stride=stride,
                  prompt_buckets=(prompt_t,), paged=paged,
                  page_size=page_size, kv_int8=kv_int8,
                  kv_bits=kv_bits,
                  evict_policy=evict_policy, evict_param=evict_param,
                  prefix_cache=prefix_cache, chunked_prefill=chunked,
                  spec_gamma=spec_gamma, draft_layers=draft_layers,
                  fused_ticks=fused_k,
                  tracer=tracer, trace_ctx=trace_ctx)
    if paged and dp > 1:
        from kubegpu_tpu.models.serve import DataParallelServePool
        eng = DataParallelServePool(params, cfg, dp=dp, tp=tp,
                                    **eng_kw)
    elif paged and tp > 1:
        from kubegpu_tpu.models.serve import make_serve_mesh
        eng = ContinuousBatcher(params, cfg,
                                mesh=make_serve_mesh(tp), **eng_kw)
    else:
        tp = dp = 1
        eng = ContinuousBatcher(params, cfg, **eng_kw)
    # compile every wave size + the decode block OUTSIDE the timed
    # window; warmup() is state-free, so the occupancy gauge stays
    # pure steady state
    t_w0 = time.perf_counter()
    eng.warmup()
    warmup_s = time.perf_counter() - t_w0
    t0 = time.perf_counter()
    for i in range(n_reqs):
        # arrays, not python lists: converting a 1024-long list costs
        # ~ms per submit and lands inside the measured window
        eng.submit((base + i) % cfg.vocab_size, steps)
    done = eng.drain()
    elapsed = time.perf_counter() - t0
    total = sum(len(r.tokens) for r in done)
    ok = len(done) == n_reqs and all(
        0 <= t < cfg.vocab_size for r in done for t in r.tokens)
    if env.worker_id == 0:
        common = {
            "unit": "tokens/s", "mode": "continuous",
            "slots": n_slots, "prompt": prompt_t, "steps": steps,
            "requests": n_reqs, "int8": int8,
            "devices": jax.device_count(),
        }
        print(json.dumps({
            "metric": "serve_engine_tokens_per_s",
            "value": round(total / elapsed, 1), **common,
        }))
        print(json.dumps({
            "metric": "serve_engine_occupancy",
            "value": round(eng.occupancy, 4), "unit": "fraction",
        }))
        # config echo + phase timings for pod-tax attribution
        # (VERDICT r5 next-item #3) — everything the library A/B needs
        # to reproduce this engine exactly, as harvestable numerics
        from kubegpu_tpu.obs.metrics import percentiles
        stall = percentiles(eng.stall_ms)
        for name, value in (
                ("serve_engine_cfg_slots", n_slots),
                ("serve_engine_cfg_prompt", prompt_t),
                ("serve_engine_cfg_steps", steps),
                ("serve_engine_cfg_stride", stride),
                ("serve_engine_cfg_requests", n_reqs),
                ("serve_engine_cfg_paged", int(paged)),
                # mesh config echo: the scheduler's topology score and
                # the harvested tok/s must describe the same slice
                ("serve_engine_cfg_tp", tp),
                ("serve_engine_cfg_dp", dp),
                ("serve_engine_cfg_mesh_devices", tp * dp),
                ("serve_engine_cfg_kv_int8", int(kv_int8)),
                ("serve_engine_cfg_int8_weights", int(int8)),
                ("serve_engine_cfg_prefix_cache", int(prefix_cache)),
                ("serve_engine_cfg_chunked_prefill", int(chunked)),
                # speculative-serving echo: the harvested tok/s and
                # the acceptance that produced it travel together, so
                # the scheduler/registry sees drafting quality per pod
                ("serve_engine_cfg_spec_gamma", spec_gamma),
                # fused-decode echo (ISSUE 8): the ceiling asked for
                # and how many fused blocks actually ran — a harvested
                # zero here with fused_k > 1 means the window never
                # reached steady state
                ("serve_engine_cfg_fused_k", fused_k),
                ("serve_fused_dispatches",
                 eng.fused_dispatches if hasattr(eng, "fused_dispatches")
                 else sum(e.fused_dispatches for e in eng.replicas)),
                ("serve_engine_cfg_draft_layers",
                 getattr(eng, "draft_layers",
                         eng.replicas[0].draft_layers
                         if hasattr(eng, "replicas") else 0)),
                ("serve_engine_spec_accept_rate",
                 round(eng.spec_acceptance_rate, 4)),
                ("serve_engine_spec_tokens_per_tick",
                 round(eng.spec_tokens_per_tick, 3)),
                ("serve_engine_phase_warmup_ms",
                 round(warmup_s * 1e3, 1)),
                ("serve_engine_phase_drain_ms",
                 round(elapsed * 1e3, 1)),
                ("serve_engine_waves", eng.prefill_waves),
                ("serve_engine_ticks",
                 eng.slot_steps // (stride * n_slots)),
                ("serve_engine_stall_p50_ms",
                 round(stall["p50"], 3)),
                ("serve_engine_stall_p99_ms",
                 round(stall["p99"], 3)),
                # fault-tolerance echo (ISSUE 4): zeros on a healthy
                # run, but harvested unconditionally so the
                # scheduler's serving_metrics() surface carries the
                # failover story per pod (a slice whose serving pods
                # fail over is a health signal, not pod-log noise)
                ("serve_failover_total",
                 getattr(eng, "failovers", 0)),
                ("serve_requests_retried",
                 getattr(eng, "requests_retried_total",
                         eng.requests_retried)),
                ("serve_slots_quarantined", eng.slots_quarantined),
                ("serve_requests_shed",
                 eng.requests_shed if hasattr(eng, "requests_shed")
                 else sum(e.requests_shed for e in eng.replicas)),
                # HBM accounting echo (ISSUE 10): live/peak pool bytes
                # at the engine's dispatch boundaries — with buffer
                # donation this sits at ~1× the pool; ~2× means
                # donation silently stopped aliasing on this build
                ("serve_hbm_pool_bytes", eng.hbm_pool_bytes),
                ("serve_hbm_peak_bytes", eng.hbm_peak_bytes),
                # overload echo (ISSUE 13): zeros on an unloaded run,
                # harvested unconditionally so serving_metrics() can
                # mirror the shed/preempt/deadline pressure per pod;
                # with no tiers configured every request is
                # best-effort, so goodput-under-SLO degenerates to
                # the raw tokens/s above
                ("serve_goodput_tokens_per_s",
                 round(total / elapsed, 1)),
                ("serve_requests_preempted",
                 getattr(eng, "requests_preempted", 0)),
                ("serve_requests_resumed",
                 getattr(eng, "requests_resumed", 0)),
                ("serve_deadline_miss",
                 getattr(eng, "deadline_misses", 0)),
                # closed-loop echo (ISSUE 14): routing affinity and
                # autoscale state per pod — a bare engine echoes the
                # single-replica identity (1 replica, no routing)
                ("serve_routing_affinity_hits",
                 getattr(eng, "routing_affinity_hits", 0)),
                ("serve_autoscale_events",
                 getattr(eng, "autoscale_events", 0)),
                ("serve_replicas_active",
                 len(eng._alive()) if hasattr(eng, "_alive") else 1),
                # kv compression & eviction echo (ISSUE 15): the pod's
                # kv format, how many resident pages the eviction
                # policy dropped, and the measured quality delta (0.0
                # until a harness calls note_kv_quality) — mirrored by
                # the scheduler as serving_kv_bits etc.
                ("serve_kv_bits",
                 eng.kv_bits if hasattr(eng, "kv_bits")
                 else eng.replicas[0].kv_bits),
                ("serve_pages_evicted_total",
                 eng.pages_evicted if hasattr(eng, "pages_evicted")
                 else sum(e.pages_evicted for e in eng.replicas)),
                ("serve_kv_quality_delta",
                 getattr(eng, "kv_quality_delta", 0.0))):
            print(json.dumps({"metric": name, "value": value}))
        if tracer is not None:
            # trace echo: span count is harvestable; the full Perfetto
            # JSON goes to SERVE_TRACE_OUT when asked (validated by
            # make trace-smoke)
            print(json.dumps({"metric": "serve_trace_spans",
                              "value": len(tracer.spans())}))
            trace_out = os.environ.get("SERVE_TRACE_OUT")
            if trace_out:
                with open(trace_out, "w") as f:
                    f.write(tracer.to_chrome_trace())
    if not ok:
        print("FAIL: continuous engine dropped or corrupted requests",
              file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Fake apiserver — the in-process control plane all components talk through.

Reference parity (SURVEY.md §2 key property + §5): scheduler ↔ node agent
coordination flows exclusively through apiserver objects; tests run the real
scheduler/crishim code against this fake with identical semantics: objects
with resourceVersion bumps, strategic-merge-style annotation patches, list
with label selectors, and watch (delivered synchronously to subscribers —
the informer pattern without goroutines).

Thread-safe: the scheduler loop, advertiser ticks, and workload runtimes may
touch it from different threads (SURVEY.md §6 race-detection requirement —
stress-tested in tests/test_controlplane.py).
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Iterator

from kubegpu_tpu.kubemeta.objects import Node, Pod


@dataclass(frozen=True)
class WatchEvent:
    kind: str      # "Pod" | "Node"
    type: str      # "ADDED" | "MODIFIED" | "DELETED"
    obj: object    # deep copy — consumers cannot mutate server state


class Conflict(Exception):
    """resourceVersion mismatch on update — caller must re-read and retry."""


class NotFound(Exception):
    pass


@dataclass
class _Store:
    objects: dict[str, object] = field(default_factory=dict)


class FakeApiServer:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._stores: dict[str, _Store] = {
            "Pod": _Store(), "Node": _Store(), "Quota": _Store()}
        self._watchers: list[Callable[[WatchEvent], None]] = []
        self._rv = 0
        # Watch delivery happens OUTSIDE self._lock: a callback that
        # re-enters a component holding its own lock (the scheduler's)
        # while another thread holds that lock and calls into the
        # apiserver would otherwise ABBA-deadlock.  Events queue under
        # self._lock (FIFO order fixed by mutation order) and a single
        # drainer at a time delivers them; _drain never blocks on the
        # delivery lock, so a thread that queued while a drain is active
        # just leaves its event for the active drainer.
        self._delivery = threading.Lock()
        self._pending_events: deque[WatchEvent] = deque()

    # -- internals -------------------------------------------------------

    def _bump(self, obj) -> None:
        self._rv += 1
        obj.metadata.resource_version = self._rv

    def _notify(self, ev: WatchEvent) -> None:
        """Queue an event (called under self._lock); delivery is via
        :meth:`_drain` after the mutator releases the lock."""
        self._pending_events.append(ev)

    def _drain(self) -> None:
        if not self._delivery.acquire(blocking=False):
            return   # an active drainer will deliver our queued event
        try:
            while True:
                with self._lock:
                    if not self._pending_events:
                        return
                    ev = self._pending_events.popleft()
                    watchers = list(self._watchers)
                for w in watchers:
                    w(ev)
        finally:
            self._delivery.release()

    @staticmethod
    def _key(namespace: str, name: str) -> str:
        return f"{namespace}/{name}"

    # -- CRUD ------------------------------------------------------------

    def create(self, kind: str, obj) -> object:
        with self._lock:
            store = self._stores[kind]
            key = self._key(obj.metadata.namespace, obj.metadata.name)
            if key in store.objects:
                raise Conflict(f"{kind} {key} already exists")
            self._bump(obj)
            store.objects[key] = obj.clone()
            self._notify(WatchEvent(kind, "ADDED", obj.clone()))
            out = obj.clone()
        self._drain()
        return out

    def get(self, kind: str, name: str, namespace: str = "default"):
        with self._lock:
            store = self._stores[kind]
            key = self._key(namespace, name)
            if key not in store.objects:
                raise NotFound(f"{kind} {key}")
            return store.objects[key].clone()

    def list(self, kind: str, label_selector: dict[str, str] | None = None,
             *, node_name: str | None = None, phase=None,
             namespace: str | None = None):
        """``node_name``/``phase``/``namespace`` are field selectors (k8s
        ``spec.nodeName=...``/``status.phase=...``/namespace scoping):
        filtering happens BEFORE the per-object copy, so a node agent
        asking for its own scheduled pods doesn't pay for cloning the
        whole cluster.  ``phase`` accepts one PodPhase or a tuple of
        them.  node_name/phase are Pod-only selectors; namespace works
        for any kind."""
        if (node_name is not None or phase is not None) and kind != "Pod":
            raise ValueError(
                f"node_name/phase are Pod field selectors (kind={kind})")
        if phase is not None and not isinstance(phase, tuple):
            phase = (phase,)
        with self._lock:
            out = []
            for obj in self._stores[kind].objects.values():
                if label_selector and any(
                    obj.metadata.labels.get(k) != v
                    for k, v in label_selector.items()
                ):
                    continue
                if namespace is not None \
                        and obj.metadata.namespace != namespace:
                    continue
                if node_name is not None \
                        and obj.spec.node_name != node_name:
                    continue
                if phase is not None and obj.status.phase not in phase:
                    continue
                out.append(obj.clone())
            return out

    def update(self, kind: str, obj) -> object:
        """Optimistic-concurrency replace: resourceVersion must match."""
        with self._lock:
            store = self._stores[kind]
            key = self._key(obj.metadata.namespace, obj.metadata.name)
            if key not in store.objects:
                raise NotFound(f"{kind} {key}")
            current = store.objects[key]
            if obj.metadata.resource_version != current.metadata.resource_version:
                raise Conflict(
                    f"{kind} {key}: rv {obj.metadata.resource_version} != "
                    f"{current.metadata.resource_version}")
            self._bump(obj)
            store.objects[key] = obj.clone()
            self._notify(WatchEvent(kind, "MODIFIED", obj.clone()))
            out = obj.clone()
        self._drain()
        return out

    def patch_annotations(self, kind: str, name: str,
                          annotations: dict[str, str | None],
                          namespace: str = "default"):
        """Strategic-merge patch of annotations only — the reference's
        ``client-go Patch`` path used by the advertiser and the allocation
        write-back (SURVEY.md §4.1/§4.2).  Never conflicts.  A ``None``
        value DELETES the key (k8s strategic-merge null semantics).
        """
        with self._lock:
            store = self._stores[kind]
            key = self._key(namespace, name)
            if key not in store.objects:
                raise NotFound(f"{kind} {key}")
            obj = store.objects[key]
            for k, v in annotations.items():
                if v is None:
                    obj.metadata.annotations.pop(k, None)
                else:
                    obj.metadata.annotations[k] = v
            self._bump(obj)
            self._notify(WatchEvent(kind, "MODIFIED", obj.clone()))
            out = obj.clone()
        self._drain()
        return out

    def bind_pod(self, name: str, node_name: str,
                 namespace: str = "default") -> None:
        """The scheduler's bind verb (kube-scheduler posts a Binding)."""
        from kubegpu_tpu.kubemeta.objects import PodPhase
        with self._lock:
            key = self._key(namespace, name)
            pod = self._stores["Pod"].objects.get(key)
            if pod is None:
                raise NotFound(f"Pod {key}")
            pod.spec.node_name = node_name
            pod.status.phase = PodPhase.SCHEDULED
            self._bump(pod)
            self._notify(WatchEvent("Pod", "MODIFIED", pod.clone()))
        self._drain()

    def set_pod_phase(self, name: str, phase, message: str = "",
                      exit_code: int | None = None,
                      namespace: str = "default",
                      expect_uid: str | None = None) -> None:
        """``expect_uid`` makes the write incarnation-safe: if the pod was
        deleted and recreated under the same name (gang eviction) between
        the caller's read and this write, the stale write is rejected as
        NotFound instead of stamping the new pod's phase."""
        with self._lock:
            key = self._key(namespace, name)
            pod = self._stores["Pod"].objects.get(key)
            if pod is None:
                raise NotFound(f"Pod {key}")
            if expect_uid is not None and pod.metadata.uid != expect_uid:
                raise NotFound(f"Pod {key} uid {pod.metadata.uid} != "
                               f"{expect_uid} (recreated)")
            pod.status.phase = phase
            pod.status.message = message
            if exit_code is not None:
                pod.status.exit_code = exit_code
            self._bump(pod)
            self._notify(WatchEvent("Pod", "MODIFIED", pod.clone()))
        self._drain()

    def set_node_ready(self, name: str, ready: bool,
                       namespace: str = "default") -> None:
        """Node-lifecycle verb (node controller marking NotReady on missed
        heartbeats — the k8s-native failure detection SURVEY.md §6 says the
        reference relied on)."""
        with self._lock:
            key = self._key(namespace, name)
            node = self._stores["Node"].objects.get(key)
            if node is None:
                raise NotFound(f"Node {key}")
            if node.status.ready == ready:
                return
            node.status.ready = ready
            self._bump(node)
            self._notify(WatchEvent("Node", "MODIFIED", node.clone()))
        self._drain()

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        with self._lock:
            store = self._stores[kind]
            key = self._key(namespace, name)
            if key not in store.objects:
                raise NotFound(f"{kind} {key}")
            obj = store.objects.pop(key)
            self._notify(WatchEvent(kind, "DELETED", obj.clone()))
        self._drain()

    # -- watch -----------------------------------------------------------

    def watch(self, callback: Callable[[WatchEvent], None]) -> Callable[[], None]:
        """Subscribe; returns an unsubscribe function.  Events fire inside
        the mutating call (synchronous informer) — callbacks must not
        re-enter the apiserver with blocking writes from another thread.
        """
        with self._lock:
            self._watchers.append(callback)
        def unsubscribe() -> None:
            with self._lock:
                if callback in self._watchers:
                    self._watchers.remove(callback)
        return unsubscribe

    # -- convenience -----------------------------------------------------

    def pods(self) -> Iterator[Pod]:
        yield from self.list("Pod")

    def nodes(self) -> Iterator[Node]:
        yield from self.list("Node")

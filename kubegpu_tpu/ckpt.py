"""Checkpoint/resume utility — the workload-side half of the elastic
story (SURVEY.md §6: annotations are the SCHEDULER's durable state; a
rescheduled gang's training state is the workload's, via orbax).

:class:`TrainCheckpointer` wraps ``orbax.checkpoint.CheckpointManager``
with the three things every KubeTPU workload needs and llama_pjit
previously hand-rolled:

- **restore-or-init**: resume from the latest step if one exists —
  params AND optimizer state (resetting adamw moments on reschedule is
  a silent training regression) — else start at step 0;
- **sharding-aware restore**: restored arrays are ``device_put`` onto
  the caller's NamedSharding tree, so a gang that comes back on a
  different slice (the fault-recovery path) re-lays out its state for
  the new mesh;
- **retention + cadence**: ``save_interval_steps`` gates how often
  ``maybe_save`` actually writes; orbax's ``max_to_keep`` bounds disk.

Checkpoint layout is orbax-standard, so checkpoints written by one
workload restore anywhere orbax runs.
"""

from __future__ import annotations

from typing import Any


class TrainCheckpointer:
    def __init__(self, directory: str, max_to_keep: int | None = None,
                 save_interval_steps: int = 1):
        """``max_to_keep=None`` retains every checkpoint (orbax's own
        default, and what the workloads did before this utility —
        silent deletion of resume history is an opt-IN)."""
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = directory
        self.save_interval_steps = max(1, save_interval_steps)
        self.manager = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True))

    @property
    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def restore_or_init(self, state: dict, shardings: dict | None = None
                        ) -> tuple[dict, int]:
        """(state, next_step): the latest checkpoint restored, or the
        given initial ``state`` at step 0.

        ``state`` is a top-level dict (the ``{"params": ...,
        "opt_state": ...}`` convention); ``shardings`` maps a SUBSET of
        its keys to NamedSharding trees — those entries are
        ``device_put`` onto their mesh layout after restore (the gang
        may have come back on a different slice), the rest keep orbax's
        placement."""
        import jax

        if shardings:
            # validate BEFORE touching disk: a bad key must not surface
            # as an orbax structure error on an unrelated template
            unknown = set(shardings) - set(state)
            if unknown:
                raise KeyError(f"shardings for unknown state keys "
                               f"{sorted(unknown)}")
        latest = self.manager.latest_step()
        if latest is None:
            return state, 0
        restored = self.manager.restore(
            latest, args=self._ocp.args.StandardRestore(state))
        if shardings:
            restored = {**restored,
                        **{k: jax.device_put(restored[k], s)
                           for k, s in shardings.items()}}
        return restored, latest + 1

    def maybe_save(self, step: int, state: Any) -> bool:
        """Save iff ``step`` is on the cadence; returns whether it did."""
        if (step + 1) % self.save_interval_steps:
            return False
        self.save(step, state)
        return True

    def save(self, step: int, state: Any) -> None:
        self.manager.save(step,
                          args=self._ocp.args.StandardSave(state))

    def wait(self) -> None:
        """Block until async saves are durable (call before exiting —
        a gang member killed mid-save must not leave a torn step as
        'latest')."""
        self.manager.wait_until_finished()

    def close(self) -> None:
        self.wait()
        self.manager.close()

"""Namespace device quotas (k8s ResourceQuota parity): the scheduler
denies asks that would push a namespace's live usage past its Quota."""

from kubegpu_tpu.cluster import SimCluster, tpu_pod
from kubegpu_tpu.kubemeta import GangSpec, PodPhase


class TestQuota:
    def test_quota_denies_over_budget_gang(self):
        cl = SimCluster(["v5e-16"])
        cl.set_quota("team-a", chips=4)
        cl.submit(tpu_pod("a1", chips=4, namespace="team-a",
                          command=["x"]))
        result, _ = cl.step()
        assert "a1" in result.scheduled
        cl.submit(tpu_pod("a2", chips=1, namespace="team-a",
                          command=["x"]))
        result, _ = cl.step()
        assert "a2" in result.unschedulable
        snap = cl.metrics.snapshot()
        assert snap["counters"]["schedule_quota_denied"] == 1.0
        cl.close()

    def test_quota_is_per_namespace(self):
        cl = SimCluster(["v5e-16"])
        cl.set_quota("team-a", chips=1)
        # team-b has no quota: unlimited
        cl.submit(tpu_pod("b1", chips=4, namespace="team-b",
                          command=["x"]))
        cl.submit(tpu_pod("a1", chips=4, namespace="team-a",
                          command=["x"]))
        result, _ = cl.step()
        assert "b1" in result.scheduled
        assert "a1" in result.unschedulable
        cl.close()

    def test_quota_frees_on_completion(self):
        cl = SimCluster(["v4-8"])
        cl.set_quota("team-a", chips=4)
        cl.submit(tpu_pod("a1", chips=4, namespace="team-a",
                          command=["x"]))
        cl.step()
        cl.submit(tpu_pod("a2", chips=2, namespace="team-a",
                          command=["x"]))
        result, _ = cl.step()
        assert "a2" in result.unschedulable
        cl.reap(timeout=0)   # a1 finishes → usage drops to 0
        result, _ = cl.step()
        assert "a2" in result.scheduled
        cl.close()

    def test_gang_counted_as_a_whole(self):
        cl = SimCluster(["v5e-16"])
        cl.set_quota("team-a", chips=4)
        cl.submit(*[
            tpu_pod(f"g-{i}", chips=2, namespace="team-a",
                    gang=GangSpec(name="g", size=4, index=i),
                    command=["x"])
            for i in range(4)   # 8 chips total > 4 quota
        ])
        result, _ = cl.step()
        assert len(result.unschedulable) == 4
        for i in range(4):
            pod = cl.api.get("Pod", f"g-{i}", namespace="team-a")
            assert pod.status.phase == PodPhase.PENDING
        cl.close()

    def test_millitpu_quota(self):
        cl = SimCluster(["v4-8"])
        cl.set_quota("team-a", millitpu=500)
        cl.submit(tpu_pod("f1", millitpu=400, namespace="team-a",
                          command=["x"]))
        result, _ = cl.step()
        assert "f1" in result.scheduled
        cl.submit(tpu_pod("f2", millitpu=400, namespace="team-a",
                          command=["x"]))
        result, _ = cl.step()
        assert "f2" in result.unschedulable
        cl.close()

    def test_multiple_quota_objects_tightest_wins(self):
        """k8s parity: every ResourceQuota in a namespace enforces
        independently, so two quota objects combine to the tighter
        limit — not just one conventionally-named object."""
        cl = SimCluster(["v5e-16"])
        cl.set_quota("team-a", chips=8, name="quota-wide")
        cl.set_quota("team-a", chips=4, name="quota-tight")
        cl.submit(tpu_pod("a1", chips=4, namespace="team-a",
                          command=["x"]))
        result, _ = cl.step()
        assert "a1" in result.scheduled
        # 4 more chips fit the wide quota (8) but not the tight one (4)
        cl.submit(tpu_pod("a2", chips=4, namespace="team-a",
                          command=["x"]))
        result, _ = cl.step()
        assert "a2" in result.unschedulable
        cl.close()

    def test_multiple_quotas_combine_per_resource(self):
        """Limits combine per RESOURCE: one object may cap chips and
        another millitpu; both apply."""
        cl = SimCluster(["v4-8"])
        cl.set_quota("team-a", chips=2, name="chips-cap")
        cl.set_quota("team-a", millitpu=400, name="frac-cap")
        cl.submit(tpu_pod("w", chips=2, namespace="team-a",
                          command=["x"]))
        cl.submit(tpu_pod("f", millitpu=300, namespace="team-a",
                          command=["x"]))
        result, _ = cl.step()
        assert {"w", "f"} <= set(result.scheduled)
        cl.submit(tpu_pod("f2", millitpu=200, namespace="team-a",
                          command=["x"]))
        result, _ = cl.step()
        assert "f2" in result.unschedulable
        cl.close()

    def test_spec_file_quotas_section(self, tmp_path):
        from kubegpu_tpu.cli import main
        spec = tmp_path / "q.yaml"
        spec.write_text(
            "cluster: {slices: [v5e-16]}\n"
            "quotas:\n"
            "  team-a: {chips: 2}\n"
            "pods:\n"
            "  - {name: ok, chips: 2, namespace: team-a, command: [x]}\n"
            "  - {name: over, chips: 2, namespace: team-a, command: [x]}\n")
        # apply schedules 'ok', denies 'over' (still pending at the end)
        rc = main(["apply", "-f", str(spec), "--schedule-only"])
        assert rc == 0

    def test_high_priority_preempts_same_namespace_for_quota(self):
        """Review regression: a priority-10 gang at the namespace quota
        ceiling must evict the tenant's own lower-priority gang rather
        than sit unschedulable forever."""
        cl = SimCluster(["v5e-16"])
        cl.set_quota("team-a", chips=4)
        cl.submit(tpu_pod("low", chips=4, namespace="team-a",
                          command=["x"], priority=0))
        result, _ = cl.step()
        assert "low" in result.scheduled
        cl.submit(tpu_pod("high", chips=4, namespace="team-a",
                          command=["x"], priority=10))
        result, _ = cl.step()
        assert "high" in result.scheduled
        low = cl.api.get("Pod", "low", namespace="team-a")
        assert low.status.phase == PodPhase.PENDING   # requeued whole
        cl.close()

    def test_quota_preemption_never_crosses_namespaces(self):
        """Quota pressure in team-a must not evict team-b's gangs (they
        free no team-a budget)."""
        cl = SimCluster(["v5e-16", "v5e-16"])
        cl.set_quota("team-a", chips=4)
        cl.submit(tpu_pod("b-low", chips=4, namespace="team-b",
                          command=["x"], priority=0))
        cl.submit(tpu_pod("a-1", chips=4, namespace="team-a",
                          command=["x"], priority=0))
        cl.step()
        cl.submit(tpu_pod("a-hi", chips=4, namespace="team-a",
                          command=["x"], priority=10))
        result, _ = cl.step()
        # a-hi preempts a-1 (same ns), b-low untouched
        assert "a-hi" in result.scheduled
        b = cl.api.get("Pod", "b-low", namespace="team-b")
        assert b.status.phase != PodPhase.PENDING
        cl.close()

    def test_same_gang_name_across_namespaces_not_conflated(self):
        """Review regression: two tenants both running a gang named
        'train' must have independent scheduler identities — quota
        preemption in one namespace must never evict the other's."""
        cl = SimCluster(["v5e-16", "v5e-16"])
        cl.set_quota("team-a", chips=8)
        for ns in ("team-a", "team-b"):
            cl.submit(*[
                tpu_pod(f"train-{i}", chips=4, namespace=ns,
                        gang=GangSpec(name="train", size=2, index=i),
                        command=["x"], priority=0)
                for i in range(2)
            ])
        result, _ = cl.step()
        assert len(result.scheduled) == 4
        assert set(cl.scheduler._committed) == {"team-a/train",
                                                "team-b/train"}
        # quota pressure in team-a evicts team-a/train only
        cl.submit(*[
            tpu_pod(f"hi-{i}", chips=4, namespace="team-a",
                    gang=GangSpec(name="hi", size=2, index=i),
                    command=["x"], priority=9)
            for i in range(2)
        ])
        result, _ = cl.step()
        assert set(result.scheduled) == {"hi-0", "hi-1"}
        for i in range(2):
            a = cl.api.get("Pod", f"train-{i}", namespace="team-a")
            b = cl.api.get("Pod", f"train-{i}", namespace="team-b")
            assert a.status.phase == PodPhase.PENDING
            assert b.status.phase != PodPhase.PENDING
        cl.close()

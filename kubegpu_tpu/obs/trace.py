"""Structured schedule trace: why each decision went the way it did.

SURVEY.md §6 "Tracing": per-decision record of the candidates considered,
scores, the winner, and phase timings — the debuggability layer the
reference lacked.

ISSUE 6: construct with ``tracer=`` to ALSO forward every recorded
decision into a :class:`~kubegpu_tpu.obs.spans.Tracer` — decisions whose
gang the extender linked to a request trace (``Tracer.link_gang``)
become instant events on that trace, so control-plane scheduling and
engine ticks land on one Perfetto timeline.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import dataclass, field, asdict


@dataclass
class TraceEvent:
    ts: float
    kind: str                   # "schedule" | "fail" | "recover" | ...
    gang: str = ""
    detail: dict = field(default_factory=dict)


class ScheduleTrace:
    def __init__(self, capacity: int = 4096, tracer=None) -> None:
        self._lock = threading.Lock()
        # deque(maxlen=) evicts O(1); the old list.pop(0) shifted the
        # whole ring every record once full — O(capacity) per decision
        # in a long-lived daemon
        self._events: deque[TraceEvent] = deque(maxlen=capacity)
        self._tracer = tracer

    def record(self, kind: str, gang: str = "", **detail) -> None:
        with self._lock:
            self._events.append(
                TraceEvent(ts=time.time(), kind=kind, gang=gang,
                           detail=detail))
        if self._tracer is not None and gang:
            self._tracer.ingest_schedule_event(kind, gang, detail)

    def events(self, kind: str | None = None) -> list[TraceEvent]:
        with self._lock:
            return [e for e in self._events
                    if kind is None or e.kind == kind]

    def to_json(self) -> str:
        with self._lock:
            return json.dumps([asdict(e) for e in self._events])

"""Annotation codec — reference: ``kubeinterface/kubeinterface.go``.

Bidirectional conversion between internal structs and annotation JSON
(SURVEY.md §3: ``NodeInfoToAnnotation`` / ``AnnotationToNodeInfo`` /
``PodInfoToAnnotation``).  Annotation keys mirror the reference's
``node.alpha/DeviceInformation`` / ``pod.alpha/DeviceInformation`` naming.

Annotations — not in-memory state — are the source of truth: the scheduler
rebuilds its cache from them after restart (SURVEY.md §4.4 correctness
subtlety), so every field the scheduler needs must round-trip losslessly.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from kubegpu_tpu.kubemeta.objects import GangSpec, Node, Pod
from kubegpu_tpu.topology.mesh import Coord
from kubegpu_tpu.tpuplugin.backend import ChipAdvertisement, NodeAdvertisement

DEVICE_INFO_KEY = "node.alpha.kubetpu/device-information"
ALLOCATE_FROM_KEY = "pod.alpha.kubetpu/allocate-from"
GANG_KEY = "pod.alpha.kubetpu/gang"
MESH_AXES_KEY = "pod.alpha.kubetpu/mesh-axes"
# workload kind ("training" default | "serving"): serving gangs carry
# a different traffic model — tp psums every decode step, dp replicas
# never talk — so the scheduler scores their slices with serving axis
# weights instead of the training defaults
WORKLOAD_KIND_KEY = "pod.alpha.kubetpu/workload-kind"
# serving role ("prefill" | "decode") on a DISAGGREGATED serving gang:
# prefill replicas are throughput-bound batch engines off the token
# feedback path, decode replicas are latency-bound — placement scores
# their slices with role-adjusted serving weights
SERVE_ROLE_KEY = "pod.alpha.kubetpu/serve-role"
MULTISLICE_KEY = "pod.alpha.kubetpu/multislice"
MIGRATABLE_KEY = "pod.alpha.kubetpu/migratable"
# original queue position of an evicted+requeued pod: eviction (fault,
# preemption, migration) must not cost a gang its FIFO seniority, or any
# equal-priority pending unit could steal the home a migration plan
# proved for it
QUEUED_AT_KEY = "pod.alpha.kubetpu/queued-at"
# a MIGRATED gang's reserved re-ask (serialized GangRequest): persisted
# on the requeued pods so a scheduler restart between migration-eviction
# and re-placement cannot drop the what-if home protection (annotation
# truth, like everything else); cleared when the gang re-places
MIGRATION_DEBT_KEY = "pod.alpha.kubetpu/migration-debt"


# ---------------------------------------------------------------------------
# Node advertisement ⇄ annotation
# ---------------------------------------------------------------------------

def node_advertisement_to_annotation(adv: NodeAdvertisement) -> str:
    return json.dumps({
        "nodeName": adv.node_name,
        "sliceId": adv.slice_id,
        "sliceType": adv.slice_type,
        "hostId": adv.host_id,
        "meshShape": list(adv.mesh_shape),
        "wrap": list(adv.wrap),
        "hostBlock": list(adv.host_block),
        "internalIp": adv.internal_ip,
        "badLinks": [[list(a), list(b)] for a, b in adv.bad_links],
        "chips": [
            {
                "coord": list(c.coord),
                "localIndex": c.local_index,
                "millichips": c.millichips,
                "hbmGib": c.hbm_gib,
                "healthy": c.healthy,
            }
            for c in adv.chips
        ],
    }, sort_keys=True)


def node_advertisement_from_annotation(payload: str) -> NodeAdvertisement:
    d = json.loads(payload)
    return NodeAdvertisement(
        node_name=d["nodeName"],
        slice_id=d["sliceId"],
        slice_type=d["sliceType"],
        host_id=d["hostId"],
        mesh_shape=tuple(d["meshShape"]),
        wrap=tuple(bool(w) for w in d["wrap"]),
        host_block=tuple(d["hostBlock"]),
        internal_ip=d.get("internalIp", "127.0.0.1"),
        bad_links=tuple(
            (tuple(a), tuple(b)) for a, b in d.get("badLinks", [])),
        chips=tuple(
            ChipAdvertisement(
                coord=tuple(c["coord"]),
                local_index=c["localIndex"],
                millichips=c["millichips"],
                hbm_gib=c["hbmGib"],
                healthy=c.get("healthy", True),
            )
            for c in d["chips"]
        ),
    )


def advertise_on_node(node: Node, adv: NodeAdvertisement) -> None:
    node.metadata.annotations[DEVICE_INFO_KEY] = \
        node_advertisement_to_annotation(adv)


def node_advertisement(node: Node) -> NodeAdvertisement | None:
    payload = node.metadata.annotations.get(DEVICE_INFO_KEY)
    return node_advertisement_from_annotation(payload) if payload else None


# ---------------------------------------------------------------------------
# Allocation (AllocateFrom) ⇄ pod annotation
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class AllocatedChip:
    coord: Coord
    local_index: int
    millichips: int  # how much of the chip this pod holds


@dataclass
class Allocation:
    """The scheduler's concrete decision for one pod — reference:
    ``ContainerInfo.AllocateFrom`` (requested resource → device path),
    written back as a pod annotation at bind time (SURVEY.md §4.2) and read
    by the crishim at container-create time (SURVEY.md §4.3).
    """

    node_name: str
    slice_id: str
    chips: list[AllocatedChip] = field(default_factory=list)
    worker_id: int = 0
    num_workers: int = 1
    coordinator_address: str = ""
    worker_hostnames: list[str] = field(default_factory=list)
    gang_name: str = ""


def allocation_to_annotation(alloc: Allocation) -> str:
    return json.dumps({
        "nodeName": alloc.node_name,
        "sliceId": alloc.slice_id,
        "chips": [
            {"coord": list(c.coord), "localIndex": c.local_index,
             "millichips": c.millichips}
            for c in alloc.chips
        ],
        "workerId": alloc.worker_id,
        "numWorkers": alloc.num_workers,
        "coordinatorAddress": alloc.coordinator_address,
        "workerHostnames": alloc.worker_hostnames,
        "gangName": alloc.gang_name,
    }, sort_keys=True)


def allocation_from_annotation(payload: str) -> Allocation:
    d = json.loads(payload)
    return Allocation(
        node_name=d["nodeName"],
        slice_id=d["sliceId"],
        chips=[
            AllocatedChip(coord=tuple(c["coord"]),
                          local_index=c["localIndex"],
                          millichips=c["millichips"])
            for c in d["chips"]
        ],
        worker_id=d["workerId"],
        num_workers=d["numWorkers"],
        coordinator_address=d.get("coordinatorAddress", ""),
        worker_hostnames=list(d.get("workerHostnames", [])),
        gang_name=d.get("gangName", ""),
    )


def set_pod_allocation(pod: Pod, alloc: Allocation) -> None:
    pod.metadata.annotations[ALLOCATE_FROM_KEY] = \
        allocation_to_annotation(alloc)


def pod_allocation(pod: Pod) -> Allocation | None:
    payload = pod.metadata.annotations.get(ALLOCATE_FROM_KEY)
    return allocation_from_annotation(payload) if payload else None


# ---------------------------------------------------------------------------
# Gang + mesh-axes pod annotations
# ---------------------------------------------------------------------------

def set_pod_gang(pod: Pod, gang: GangSpec) -> None:
    pod.metadata.annotations[GANG_KEY] = json.dumps(
        {"name": gang.name, "size": gang.size, "index": gang.index})


def pod_gang_spec(pod: Pod) -> GangSpec | None:
    payload = pod.metadata.annotations.get(GANG_KEY)
    if not payload:
        return None
    d = json.loads(payload)
    return GangSpec(name=d["name"], size=d["size"], index=d["index"])


def set_pod_mesh_axes(pod: Pod, axes: dict[str, int]) -> None:
    """Declares the workload's logical parallelism axes (ordered), e.g.
    ``{"dp": 4, "tp": 4}`` — the scheduler's topology-scoring derives the
    traffic model from this (SURVEY.md §8 "Honest locality measurement").
    """
    pod.metadata.annotations[MESH_AXES_KEY] = json.dumps(list(axes.items()))


def pod_mesh_axes(pod: Pod) -> dict[str, int] | None:
    payload = pod.metadata.annotations.get(MESH_AXES_KEY)
    if not payload:
        return None
    return dict((k, int(v)) for k, v in json.loads(payload))


def set_pod_workload_kind(pod: Pod, kind: str) -> None:
    """Declare the workload kind driving the traffic model ("training"
    is the implicit default; "serving" switches topology scoring to
    serving axis weights — tp hot, dp-replica hops nearly free)."""
    if kind not in ("training", "serving"):
        raise ValueError(f"unknown workload kind {kind!r}")
    pod.metadata.annotations[WORKLOAD_KIND_KEY] = kind


def pod_workload_kind(pod: Pod) -> str:
    return pod.metadata.annotations.get(WORKLOAD_KIND_KEY, "training")


def set_pod_serve_role(pod: Pod, role: str) -> None:
    """Annotate a serving pod with its disaggregated role: "prefill"
    replicas run chunked prefill and export KV page chains, "decode"
    replicas adopt them and stream tokens.  Placement reads the role
    through :func:`pod_serve_role` to pick role-aware axis weights."""
    if role not in ("prefill", "decode"):
        raise ValueError(f"unknown serve role {role!r}")
    pod.metadata.annotations[SERVE_ROLE_KEY] = role


def pod_serve_role(pod: Pod) -> str | None:
    """The pod's disaggregated serving role, or None on a symmetric
    (or non-serving) pod."""
    return pod.metadata.annotations.get(SERVE_ROLE_KEY)


def set_pod_migratable(pod: Pod, allowed: bool = True) -> None:
    """Mark the pod's gang as migratable: the scheduler may evict and
    requeue it (checkpoint/resume semantics, like fault recovery) to
    defragment space for an otherwise-unplaceable gang."""
    if allowed:
        pod.metadata.annotations[MIGRATABLE_KEY] = "true"
    else:
        pod.metadata.annotations.pop(MIGRATABLE_KEY, None)


def pod_migratable(pod: Pod) -> bool:
    return pod.metadata.annotations.get(MIGRATABLE_KEY) == "true"


def set_pod_multislice(pod: Pod, allowed: bool = True) -> None:
    """Opt the pod's gang into DCN-spanning placement: when no single
    slice fits, the outermost mesh axis may partition across slices."""
    if allowed:
        pod.metadata.annotations[MULTISLICE_KEY] = "true"
    else:
        pod.metadata.annotations.pop(MULTISLICE_KEY, None)


def pod_multislice(pod: Pod) -> bool:
    return pod.metadata.annotations.get(MULTISLICE_KEY) == "true"


def migration_debt_to_annotation(req: "GangRequest") -> str:
    """Serialize a migrated gang's reserved re-ask (``MIGRATION_DEBT_KEY``
    payload).  Lives here with every other annotation codec so the wire
    format has one home; ``GangRequest`` is imported lazily because the
    allocator itself imports this module."""
    return json.dumps({
        "numPods": req.num_pods,
        "chipsPerPod": req.chips_per_pod,
        "millitpuPerPod": req.millitpu_per_pod,
        "hbmGibPerChip": req.hbm_gib_per_chip,
        "meshAxes": (list(req.mesh_axes.items())
                     if req.mesh_axes else None),
        "allowMultislice": req.allow_multislice,
    }, sort_keys=True)


def migration_debt_from_annotation(gang_key: str,
                                   payload: str) -> "GangRequest | None":
    from kubegpu_tpu.allocator.gang import GangRequest

    try:
        d = json.loads(payload)
        return GangRequest(
            gang_name=gang_key,
            num_pods=int(d["numPods"]),
            chips_per_pod=int(d["chipsPerPod"]),
            millitpu_per_pod=int(d.get("millitpuPerPod", 0)),
            hbm_gib_per_chip=float(d.get("hbmGibPerChip", 0.0)),
            mesh_axes=dict((k, int(v)) for k, v in d["meshAxes"])
            if d.get("meshAxes") else None,
            allow_multislice=bool(d.get("allowMultislice", False)))
    except (ValueError, KeyError, TypeError):
        return None   # malformed debt: drop the reservation, not the pod

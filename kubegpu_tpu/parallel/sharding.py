"""Sharding utilities: PartitionSpec trees → NamedShardings, activation
constraints that degrade gracefully off-mesh."""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _fix_axis(a, names: set[str]):
    if a is None:
        return None
    if isinstance(a, (tuple, list)):
        kept = tuple(x for x in a if x in names)
        return kept if kept else None
    return a if a in names else None


def fit_spec(mesh: Mesh, spec: P) -> P:
    """Drop axis names ``mesh`` doesn't have, so one rule set serves
    dp-only and dp×fsdp×tp meshes alike."""
    names = set(mesh.axis_names)
    return P(*(_fix_axis(a, names) for a in spec))


def named_sharding_tree(mesh: Mesh, spec_tree):
    """Map a pytree of PartitionSpec to NamedSharding over ``mesh``."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, fit_spec(mesh, s)), spec_tree,
        is_leaf=lambda x: isinstance(x, P))


def constrain(x: jax.Array, mesh: Mesh | None, *spec) -> jax.Array:
    """``with_sharding_constraint`` against ``mesh``; identity when no mesh
    is in play (single-device tests, the driver's single-chip entry)."""
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, fit_spec(mesh, P(*spec))))


def device_put_tree(mesh: Mesh, tree, spec_tree):
    """``device_put`` a pytree against a matching PartitionSpec tree.

    The serving engine lays out its big state ONCE at construction (the
    page pool over KV heads, full and draft weights megatron-style per
    ``_serve_param_specs``) so every per-tick executable sees inputs
    already placed per its ``in_specs`` — no per-dispatch resharding.
    QTensor-style container leaves work transparently: both ``tree``
    and ``spec_tree`` carry them as pytree nodes, so values and scales
    pick up their own specs in lockstep."""
    sharding = jax.tree.map(
        lambda s: NamedSharding(mesh, fit_spec(mesh, s)), spec_tree,
        is_leaf=lambda x: isinstance(x, P))
    return jax.device_put(tree, sharding)


def donating_jit(f, donate=(), static=(), mesh=None, in_specs=None,
                 out_specs=None):
    """The serving hot path's one wrapping: ``jax.jit`` with buffer
    donation, composed with ``compat_shard_map`` when a mesh is in
    play.  ``donate`` names arguments of ``f`` whose buffers the
    caller rebinds every dispatch (the page pool, the per-slot token/
    pos mirrors); XLA then writes each output INTO its input's buffer
    instead of keeping both live — the difference between 1× and 2×
    steady-state KV HBM.  Donation is per-ARGUMENT, so a container
    arg donates every pytree leaf together: an int8 pool's
    ``k_scale``/``v_scale`` (QTensor-style value+scale pairs) alias
    alongside ``k``/``v`` with no extra spelling.

    ``static`` names compile-time arguments (``static_argnames``).
    Off-mesh that is plain jit; ON-mesh shard_map has no static
    story, so the static values are bound into the body with
    ``functools.partial`` at trace time and the outer jit keeps both
    the donation and the static names (resolved against ``f``'s own
    signature through ``__wrapped__``).

    Callers must rebind from the outputs and drop every stale
    reference — a read of a donated buffer after dispatch raises
    ``RuntimeError: Array has been deleted`` (the engine's debug
    guard makes that loud on every backend, see
    ``ContinuousBatcher``)."""
    import functools

    donate = tuple(donate)
    static = tuple(static)
    if mesh is None:
        return jax.jit(f, donate_argnames=donate,
                       static_argnames=static)
    if not static:
        mapped = compat_shard_map(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, check=False)

        @functools.wraps(f)
        def call(*args):
            return mapped(*args)

        return jax.jit(call, donate_argnames=donate)

    import inspect
    sig = inspect.signature(f)

    @functools.wraps(f)
    def call(*args, **kwargs):
        bound = sig.bind(*args, **kwargs)
        bound.apply_defaults()
        sta = {n: bound.arguments.pop(n) for n in static}
        mapped = compat_shard_map(
            functools.partial(f, **sta), mesh=mesh, in_specs=in_specs,
            out_specs=out_specs, check=False)
        return mapped(*bound.arguments.values())

    return jax.jit(call, donate_argnames=donate,
                   static_argnames=static)


def sharded_jit(f, mesh: Mesh, in_specs, out_specs, donate=()):
    """``compat_shard_map`` + ``jax.jit`` with buffer donation, in one
    call — kept as the mesh-only spelling of :func:`donating_jit`
    (train-step call sites predate the shared helper)."""
    return donating_jit(f, donate=donate, mesh=mesh,
                        in_specs=in_specs, out_specs=out_specs)


def donation_aliases(fn, *args, **kwargs) -> set[int]:
    """Flat input-parameter indices the COMPILED executable aliases to
    outputs, read from the ``input_output_alias`` header of the
    optimized HLO (``fn.lower(...).compile().as_text()``) — the
    ground truth of what XLA will actually reuse in place, not what
    jit was asked to donate.  Indices count pytree LEAVES of the
    non-static arguments in signature order (a donated pool dict
    contributes one index per leaf: k, v, and the int8 scales).

    Caveat: jit drops unused parameters from the lowering
    (``keep_unused=False``), which would shift indices — every
    serving executable uses all of its arguments, so the flat order
    here is exact for them."""
    import re

    txt = fn.lower(*args, **kwargs).compile().as_text()
    tag = "input_output_alias={"
    start = txt.find(tag)
    if start < 0:
        return set()
    # balanced-brace scan: the header nests output-index braces
    # ({ {0}: (0, {}, may-alias), ... }) so a lazy regex underruns
    i, depth = start + len(tag) - 1, 0
    while i < len(txt):
        depth += {"{": 1, "}": -1}.get(txt[i], 0)
        if depth == 0:
            break
        i += 1
    return {int(p) for p in
            re.findall(r"\}:\s*\((\d+)",
                       txt[start + len(tag):i])}


def donation_coverage(fn, args, donate, static=None) -> dict:
    """Compile ``fn`` on ``args`` and report whether every DONATED
    argument is fully aliased in place by the executable.  Returns
    ``{"aliased_params", "covered", "args": {name: {"leaves",
    "aliased", "covered"}}}`` — the bench row and the smoke test
    assert ``covered`` per executable, so a refactor that silently
    voids donation (layout mismatch, a dropped ``donate=``) fails in
    tier-1, not as an HBM regression on hardware."""
    import inspect

    kwargs = dict(static or {})
    aliased = donation_aliases(fn, *args, **kwargs)
    names = [p for p in inspect.signature(fn).parameters
             if p not in kwargs]
    report, idx, ok = {}, 0, True
    for name, val in zip(names, args):
        n = len(jax.tree.leaves(val))
        got = sum(1 for i in range(idx, idx + n) if i in aliased)
        if name in donate:
            cov = (got == n and n > 0)
            report[name] = {"leaves": n, "aliased": got,
                            "covered": cov}
            ok = ok and cov
        idx += n
    return {"aliased_params": len(aliased), "covered": ok,
            "args": report}


def compat_shard_map(f, mesh: Mesh, in_specs, out_specs, check=False):
    """shard_map across the jax API generations this repo meets: the
    driver's image has ``jax.shard_map`` (replication checking spelled
    ``check_vma``), older images only ``jax.experimental.shard_map``
    (spelled ``check_rep``).  ``check=False`` is required wherever a
    pallas_call runs inside the mapped body — pallas has no
    replication rule on either generation."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=check)
        except TypeError:   # jax.shard_map without the vma keyword
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check)

"""Coverage for the bench surfaces bench.py drives (VERDICT r1 #1):
the CPU/tiny-config path of the model bench and the full-bench document
structure must not regress silently between hardware runs."""

import math

import pytest

from kubegpu_tpu import benchmark
from kubegpu_tpu.benchmark import (
    chip_peak_tflops,
    run_full_bench,
    run_model_bench,
    train_flops_per_step,
)


class TestModelBench:
    def test_cpu_tiny_path(self):
        out = run_model_bench(steps=2)
        assert out["on_tpu"] is False
        assert out["platform"] == "cpu"
        assert math.isfinite(out["loss"])
        assert out["tokens_per_s"] > 0
        assert out["step_ms"] > 0
        assert out["params_m"] > 0
        # CPU against TPU peak: tiny (can round to 0.0000 under load)
        assert 0 <= out["mfu"] < 1
        assert out["model_tflops_per_s"] >= 0
        assert out["attention"] is None  # interpret-mode pallas not timed
        # families: every BASELINE.md hardware row must be emitted by
        # this harness (VERDICT r2 weak #2) — structure asserted on the
        # tiny CPU path so a missing row fails before a hardware run
        fam = out["families"]
        assert set(fam) == {"moe_serving", "moe_paged_engine",
                            "t5_serving", "lora",
                            "beam", "spec_decode", "spec_decode_pld",
                            "spec_decode_pld_curve",
                            "spec_decode_pld_break_even_acceptance",
                            "continuous_batching",
                            "continuous_batching_flagship",
                            "cb_prefix_cache", "cb_chunked_stall",
                            "cb_equal_hbm", "cb_spec",
                            "cb_fleet_chaos", "cb_obs_fleet"}
        curve = fam["spec_decode_pld_curve"]
        assert len(curve) >= 3
        for p in curve:
            assert 0 <= p["acceptance_rate"] <= 1
            assert p["speedup_vs_greedy"] > 0
        for row in ("continuous_batching", "continuous_batching_flagship"):
            cb = fam[row]
            assert cb["e2e_tokens_per_s_anchored"] > 0
            assert cb["decode_tokens_per_s"] > 0
            assert 0 < cb["occupancy"] <= 1
            assert cb["paged_vs_dense"] > 0
            # the same-window A/B must carry both engine modes, each
            # with the device-anchored e2e figure
            for mode in ("dense", "paged"):
                assert cb[mode]["e2e_tokens_per_s_anchored"] > 0
                assert cb[mode]["decode_tokens_per_s"] > 0
                assert cb[mode]["ticks"] > 0 and cb[mode]["waves"] > 0
        # the flagship row exercises int8 KV pages (the >=16k-pooled-
        # tokens crossover configuration)
        assert fam["continuous_batching_flagship"]["kv_int8_pages"]
        assert fam["moe_serving"]["gen_tokens_per_s_e2e"] > 0
        assert fam["t5_serving"]["gen_tokens_per_s_e2e"] > 0
        assert fam["lora"]["step_ms"] > 0
        assert fam["lora"]["trainable_params_k"] > 0
        assert fam["beam"]["e2e_ms"] > 0
        # page-pool rows for the non-flagship families (VERDICT r5 #5):
        # every paged leg measured in the same window as its dense row
        assert fam["t5_serving"]["paged"]["gen_tokens_per_s_e2e"] > 0
        assert fam["t5_serving"]["paged"]["paged_vs_dense"] > 0
        assert fam["beam"]["paged"]["e2e_ms"] > 0
        assert fam["beam"]["paged"]["paged_vs_dense"] > 0
        for leg in ("dense", "paged"):
            assert fam["moe_paged_engine"][leg][
                "decode_tokens_per_s"] > 0
        assert fam["moe_paged_engine"]["paged_vs_dense"] > 0
        # the self-draft row now measures on the in-bench-trained
        # model (VERDICT r5 next-item #7): acceptance is a real
        # number, not random-init noise
        assert fam["spec_decode"]["speedup_vs_greedy"] > 0
        assert 0 <= fam["spec_decode"]["acceptance_rate"] <= 1
        assert fam["spec_decode"]["trained_draft"] is True
        assert fam["spec_decode"]["train_steps"] > 0
        # serving fast-path rows (prefix cache / chunked stall /
        # equal-HBM) — shapes asserted in depth by test_bench_smoke
        assert fam["cb_prefix_cache"]["prefill_reduction_x"] > 1.0
        assert fam["cb_chunked_stall"]["on"]["chunk_cost_ms"] > 0
        assert fam["cb_equal_hbm"]["paged_vs_dense_equal_hbm"] > 0
        # fleet chaos row rides along host-side; deep bars live in
        # test_bench_smoke — here only presence + the headline gates
        assert fam["cb_fleet_chaos"]["exactly_once"] is True
        assert fam["cb_fleet_chaos"]["outcomes_identical"] is True
        # engine-integrated speculation rides the SAME trained model;
        # its structural bars live in test_bench_smoke — here only the
        # row's presence + parity (greedy bit-exact vs spec-off)
        for row in fam["cb_spec"]["by_tp"].values():
            if "skipped" in row:
                continue
            assert row["parity_all"] is True
            assert row["off"]["engine_tokens_per_s_anchored"] > 0

    def test_flops_scale_with_tokens(self):
        cfg = benchmark.llama_bench_config()
        f1 = train_flops_per_step(cfg, batch=1, seq=128)
        f2 = train_flops_per_step(cfg, batch=2, seq=128)
        assert f1 > 0
        # matmul term is linear in tokens; attention term superlinear in
        # seq but linear in batch → doubling batch exactly doubles flops
        assert f2 == pytest.approx(2 * f1)

    def test_peak_tflops_env_override(self, monkeypatch):
        monkeypatch.setenv("KUBETPU_PEAK_TFLOPS", "123.5")
        assert chip_peak_tflops(object()) == 123.5

    def test_peak_tflops_by_kind(self, monkeypatch):
        monkeypatch.delenv("KUBETPU_PEAK_TFLOPS", raising=False)

        class Dev:
            device_kind = "TPU v5p"
        assert chip_peak_tflops(Dev()) == 459.0


class TestFullBench:
    def test_document_structure(self, monkeypatch):
        monkeypatch.setenv("KUBETPU_BENCH_MODEL", "0")
        out = run_full_bench(n_gangs=6, seed=1)
        assert out["metric"] == "gang_schedule_p50_latency"
        assert out["unit"] == "ms"
        assert out["value"] > 0
        assert out["vs_baseline"] > 0
        assert out["details"]["decisions"] > 0
        assert "model" not in out["details"]
        # the p99 tail is attributed, not just reported
        attr = out["details"]["p99_phase_attribution"]
        assert attr["decisions"] > 0
        assert "enumerate" in attr["phases"]

    def test_model_error_does_not_hide_metric_one(self, monkeypatch):
        monkeypatch.setenv("KUBETPU_BENCH_MODEL", "1")
        monkeypatch.setattr(benchmark, "run_model_bench",
                            lambda: (_ for _ in ()).throw(RuntimeError("chip")))
        out = run_full_bench(n_gangs=4, seed=2)
        assert out["value"] > 0
        assert out["details"]["model"] == {"error": "chip"}


def test_multislice_bench_crosses_dcn():
    """The multislice scale scenario must actually exercise DCN-spanning
    gangs: some placed gangs land on >1 slice and the bench reports the
    fraction (VERDICT r3 next-item #8's done bar)."""
    from kubegpu_tpu.benchmark import run_multislice_bench
    out = run_multislice_bench(n_gangs=40, seed=0)
    d = out["details"]
    assert d["gangs_multislice"] >= 1
    assert 0 < d["multislice_fraction"] <= 1
    assert d["mean_allocation_locality"] > 0.8
    assert out["value"] >= 0


class TestSummary:
    """The driver-captured final line (VERDICT r4 next-item #1): small
    enough to survive a ~2000-char tail window whole, and carrying the
    headline metrics — above all "mfu"."""

    def _full_doc(self):
        # synthetic full document with every section present at
        # hardware-like values, so the size bound is tested against the
        # worst realistic payload, not a CPU-tiny one
        return {
            "metric": "gang_schedule_p50_latency", "value": 0.86,
            "unit": "ms", "vs_baseline": 58.14,
            "details": {
                "p90_ms": 2.1, "p99_ms": 9.4, "decisions": 88,
                "mean_allocation_locality": 0.9662,
                "model": {
                    "mfu": 0.6612, "step_ms": 219.4,
                    "tokens_per_s": 37332.1,
                    "attention": {"pallas_speedup": 3.31},
                    "serving": {
                        "decode_tokens_per_s": 4402.1,
                        "int8_decode_tokens_per_s": 6689.9,
                        "int8_kv_decode_tokens_per_s": 7001.2,
                        "int8_kv_decode_b4x_tokens_per_s": 12961.4,
                    },
                    "families": {
                        "continuous_batching": {
                            "static_e2e_tokens_per_s": 5282.0,
                            "dense": {"vs_static_e2e_anchored": 1.123},
                            "paged": {"vs_static_e2e_anchored": 1.081},
                            "decode_tokens_per_s": 8649.0,
                        },
                        "continuous_batching_flagship": {
                            "static_e2e_tokens_per_s": 13600.0,
                            "dense": {"vs_static_e2e_anchored": 1.01},
                            "paged": {"vs_static_e2e_anchored": 1.11},
                            "decode_tokens_per_s": 15100.0,
                        },
                        "cb_prefix_cache": {
                            "prefill_reduction_x": 4.267,
                            "pages_aliased": 49},
                        "cb_chunked_stall": {
                            "stall_p99_ms_off": 112.4,
                            "stall_p99_ms_on": 9.1,
                            "stall_p99_reduction_x": 12.35},
                        "cb_equal_hbm": {
                            "paged_vs_dense_equal_hbm": 1.31},
                        "cb_slo_goodput": {
                            "top_tier_goodput_ratio_x": 5.846,
                            "fifo": {
                                "goodput_tokens_per_tick": 3.02,
                                "slo_attainment": 0.78,
                                "ttft_p99_ms": 159.3},
                            "tiered": {
                                "goodput_tokens_per_tick": 4.14,
                                "slo_attainment": 1.0,
                                "ttft_p99_ms": 128.9}},
                        "spec_decode": {"speedup_vs_greedy": 1.62,
                                        "acceptance_rate": 0.84},
                        "spec_decode_pld": {
                            "speedup_vs_greedy": 2.49,
                            "acceptance_rate": 1.0},
                        "spec_decode_pld_curve": [
                            {"acceptance_rate": 0.31,
                             "speedup_vs_greedy": 0.9},
                            {"acceptance_rate": 0.52,
                             "speedup_vs_greedy": 1.4},
                            {"acceptance_rate": 0.71,
                             "speedup_vs_greedy": 1.9},
                        ],
                    },
                },
                "scheduler_scale_1024chip": {
                    "cold": {"p50_ms": 0.86,
                             "mean_allocation_locality": 0.966},
                    "steady_state": {"p50_ms": 0.90,
                                     "mean_allocation_locality": 0.966},
                },
                "scheduler_scale_multislice": {
                    "p99_ms": 10.2, "multislice_fraction": 0.16,
                    "mean_allocation_locality": 0.952,
                    "p99_phase_attribution": {
                        "phases": {
                            "enumerate": {"share": 0.21},
                            "multislice_split": {"share": 0.74},
                            "preemption_plan": {"share": 0.05}}}},
                "scheduler_wire": {"p50_ms": 1.4, "max_ms": 5.5},
                "serve_pod": {"decode_tokens_per_s": 12961.0,
                              "pod_vs_library": 0.91},
            },
        }

    def test_summary_small_and_carries_headlines(self):
        import json

        from kubegpu_tpu.benchmark import summarize_bench
        s = summarize_bench(self._full_doc())
        line = json.dumps(s)
        assert len(line) < 1500, f"summary too big: {len(line)}"
        assert s["metric"] == "gang_schedule_p50_latency"
        assert s["vs_baseline"] == 58.14
        assert s["mfu"] == 0.6612
        assert s["flash_speedup"] == 3.31
        assert s["decode_tok_s"]["int8_kv_b4x"] == 12961.4
        assert s["cb"]["paged_x"] == 1.081
        assert s["cb_flagship"]["paged_x"] == 1.11
        # serving fast-path headlines survive into the driver line
        assert s["cb_prefix"]["x"] == 4.267
        assert s["cb_stall_p99"]["x"] == 12.35
        assert s["cb_hbm_x"] == 1.31
        assert s["spec_self_x"] == 1.62
        assert s["spec_self_acc"] == 0.84
        assert s["pld"]["x"] == 2.49
        assert len(s["pld_curve"]) == 3
        assert s["sched_1024"]["cold_p50"] == 0.86
        assert s["multislice"]["frac"] == 0.16
        assert s["multislice"]["p99_top"] == "multislice_split"
        assert s["serve_pod"]["vs_lib"] == 0.91
        # goodput/SLO columns ride next to the tail columns for every
        # serving row that measured them (ISSUE 13) — sparse, so rows
        # without a load-harness run don't burn the byte budget
        assert s["serving_goodput"]["cb_slo_goodput"]["tiered"] == \
            [4.14, 1.0]
        assert s["serving_goodput"]["cb_slo_goodput"]["fifo"] == \
            [3.02, 0.78]
        assert "cb_prefix_cache" not in s["serving_goodput"]
        assert "cb_slo_goodput" in s["serving_tails"]
        assert "mfu" in line  # the driver's done-bar grep

    def test_summary_survives_errors_and_absence(self):
        import json

        from kubegpu_tpu.benchmark import summarize_bench
        doc = {"metric": "m", "value": 1.0, "unit": "ms",
               "vs_baseline": 2.0,
               "details": {"model": {"error": "chip fell over " * 30},
                           "scheduler_wire": {"error": "x"}}}
        s = summarize_bench(doc)
        line = json.dumps(s)
        assert len(line) < 1500
        assert s["model"]["error"].startswith("chip fell over")
        assert len(s["model"]["error"]) <= 120
        s2 = summarize_bench({"metric": "m", "value": 1.0})
        assert s2["metric"] == "m"

"""Multislice (DCN-spanning) gangs: a gang too big for any one slice
splits across slices on its outermost mesh axis (SURVEY.md §6 comm-backend
row: collectives ride ICI intra-slice, DCN inter-slice)."""

from kubegpu_tpu.allocator import GangAllocator, GangRequest
from kubegpu_tpu.cluster import SimCluster, tpu_pod
from kubegpu_tpu.kubemeta import GangSpec, PodPhase, pod_allocation

from tests.test_allocator import make_slice as build_slice


class TestMultisliceAllocator:
    def test_splits_when_no_single_slice_fits(self):
        """8 pods x 4 chips = 32 chips over two v5e-16s (16 each)."""
        slices = [build_slice("v5e-16", "s0"), build_slice("v5e-16", "s1")]
        req = GangRequest("g", num_pods=8, chips_per_pod=4,
                          mesh_axes={"dp": 8, "tp": 4},
                          allow_multislice=True)
        asg = GangAllocator().find_assignment(slices, req)
        assert asg is not None
        assert set(asg.slice_ids) == {"s0", "s1"}
        # contiguous worker halves per slice (outer axis partitions)
        by_slice = {}
        for p in asg.pods:
            by_slice.setdefault(asg.pod_slice(p), []).append(p.pod_index)
        assert sorted(map(sorted, by_slice.values())) == [
            [0, 1, 2, 3], [4, 5, 6, 7]]

    def test_disabled_without_opt_in(self):
        slices = [build_slice("v5e-16", "s0"), build_slice("v5e-16", "s1")]
        req = GangRequest("g", num_pods=8, chips_per_pod=4,
                          mesh_axes={"dp": 8, "tp": 4})
        assert GangAllocator().find_assignment(slices, req) is None

    def test_single_slice_still_preferred(self):
        slices = [build_slice("v5e-64", "big"), build_slice("v5e-16", "sm")]
        req = GangRequest("g", num_pods=8, chips_per_pod=4,
                          mesh_axes={"dp": 8, "tp": 4},
                          allow_multislice=True)
        asg = GangAllocator().find_assignment(slices, req)
        assert asg is not None
        assert asg.slice_ids == ["big"]

    def test_locality_counts_dcn_pairs_nonlocal(self):
        """tp traffic stays ICI-local inside each slice; the dp rings
        cross slices, so reported locality sits strictly between the
        tp-only fraction and 1.0."""
        slices = [build_slice("v5e-16", "s0"), build_slice("v5e-16", "s1")]
        req = GangRequest("g", num_pods=8, chips_per_pod=4,
                          mesh_axes={"dp": 8, "tp": 4},
                          axis_weights={"dp": 1.0, "tp": 8.0},
                          allow_multislice=True)
        asg = GangAllocator().find_assignment(slices, req)
        assert asg is not None
        assert 0.5 < asg.locality < 1.0

    def test_commit_and_rollback_span_slices(self):
        slices = [build_slice("v5e-16", "s0"), build_slice("v5e-16", "s1")]
        by_id = {s.slice_id: s for s in slices}
        alloc = GangAllocator()
        req = GangRequest("g", num_pods=8, chips_per_pod=4,
                          mesh_axes={"dp": 8, "tp": 4},
                          allow_multislice=True)
        asg = alloc.find_assignment(slices, req)
        alloc.commit(by_id, asg)
        assert all(sum(s.used_millichips.values()) == 16000 for s in slices)
        alloc.rollback(by_id, asg)
        assert all(sum(s.used_millichips.values()) == 0 for s in slices)

    def test_rollback_survives_vanished_slice(self):
        slices = [build_slice("v5e-16", "s0"), build_slice("v5e-16", "s1")]
        by_id = {s.slice_id: s for s in slices}
        alloc = GangAllocator()
        req = GangRequest("g", num_pods=8, chips_per_pod=4,
                          mesh_axes={"dp": 8, "tp": 4},
                          allow_multislice=True)
        asg = alloc.find_assignment(slices, req)
        alloc.commit(by_id, asg)
        del by_id["s1"]   # all hosts of s1 died
        alloc.rollback(by_id, asg)   # must not raise; frees s0's share
        assert sum(by_id["s0"].used_millichips.values()) == 0


class TestMultisliceCluster:
    def _submit_gang(self, cl, size=8, chips=2, name="ms"):
        cl.submit(*[
            tpu_pod(f"{name}-{i}", chips=chips,
                    gang=GangSpec(name=name, size=size, index=i),
                    mesh_axes={"dp": size, "tp": chips},
                    multislice=True, command=["x"])
            for i in range(size)
        ])

    def test_gang_spans_two_slices_end_to_end(self):
        """4 pods x 4 chips over two v4-8s (4 chips each): schedule,
        annotate (per-pod slice ids), run, release."""
        cl = SimCluster(["v4-8", "v4-8"])
        self._submit_gang(cl, size=4, chips=2, name="ms")
        result, _ = cl.step()
        assert len(result.scheduled) == 4
        slice_ids = set()
        workers = {}
        for i in range(4):
            alloc = pod_allocation(cl.api.get("Pod", f"ms-{i}"))
            slice_ids.add(alloc.slice_id)
            workers[i] = alloc.worker_id
            assert alloc.num_workers == 4
            assert alloc.coordinator_address
        assert len(slice_ids) == 2
        assert workers == {i: i for i in range(4)}
        codes = cl.run_to_completion(timeout_s=30)
        assert all(c == 0 for c in codes.values())
        # chips released on both slices
        for st in cl.scheduler.slices.values():
            assert sum(st.used_millichips.values()) == 0
        cl.close()

    def test_restart_resync_rebuilds_multislice_gang(self):
        from kubegpu_tpu.scheduler import DeviceScheduler
        cl = SimCluster(["v4-8", "v4-8"])
        self._submit_gang(cl, size=4, chips=2)
        cl.step()
        fresh = DeviceScheduler(cl.api)
        used = sum(sum(st.used_millichips.values())
                   for st in fresh.slices.values())
        assert used == 8000
        asg = fresh._committed["default/ms"]
        assert len(asg.slice_ids) == 2
        cl.close()

    def test_host_failure_evicts_whole_multislice_gang(self):
        cl = SimCluster(["v4-8", "v4-8"])
        self._submit_gang(cl, size=4, chips=2)
        result, _ = cl.step()
        assert len(result.scheduled) == 4
        # kill one host of one slice → the WHOLE gang (both slices) evicts
        victim_alloc = pod_allocation(cl.api.get("Pod", "ms-0"))
        cl.fail_host(victim_alloc.node_name)
        rec = cl.recovery.run_once()
        assert "default/ms" in rec.evicted_gangs
        for i in range(4):
            assert cl.pod_phase(f"ms-{i}") == PodPhase.PENDING
        cl.close()


class TestMultisliceRealProcesses:
    def test_dp_training_across_two_slices(self):
        """The whole path with real JAX subprocesses: a dp=4 gang split
        across two v4-8 slices forms one jax.distributed group (dp rings
        crossing the slice boundary = the DCN tier in production)."""
        cl = SimCluster(["v4-8", "v4-8"], real_processes=True,
                        extra_env={"JAX_PLATFORMS": "cpu"})
        cl.submit(*[
            tpu_pod(f"ms-{i}", chips=2,
                    gang=GangSpec(name="ms", size=4, index=i),
                    mesh_axes={"dp": 4, "tp": 2}, multislice=True,
                    command=["python", "-m",
                             "kubegpu_tpu.workloads.programs.llama_pjit"],
                    env={"LLAMA_STEPS": "1"})
            for i in range(4)
        ])
        result, _ = cl.step()
        assert len(result.scheduled) == 4, result
        codes = cl.run_to_completion(timeout_s=240)
        assert all(codes.get(f"ms-{i}") == 0 for i in range(4)), codes
        cl.close()


class TestMultisliceFaultPrecedence:
    def test_hard_fault_in_second_slice_wins_over_link_in_first(self):
        """Review regression: a bad link in the primary slice must not
        mask a DEAD host in the other slice — the gang must evict (hard),
        never park as 'degraded' with pods bound to dead hardware."""
        cl = SimCluster(["v4-8", "v4-8"])
        cl.submit(*[
            tpu_pod(f"ms-{i}", chips=2,
                    gang=GangSpec(name="ms", size=4, index=i),
                    mesh_axes={"dp": 4, "tp": 2}, multislice=True,
                    command=["x"])
            for i in range(4)
        ])
        result, _ = cl.step()
        assert len(result.scheduled) == 4
        a0 = pod_allocation(cl.api.get("Pod", "ms-0"))   # primary slice
        a2 = pod_allocation(cl.api.get("Pod", "ms-2"))   # the other one
        assert a0.slice_id != a2.slice_id
        # link fault INSIDE worker 0/1's footprint (primary, checked first)
        cl.fail_link(a0.chips[0].coord, a0.chips[1].coord,
                     slice_id=a0.slice_id)
        # hard fault: the other slice's host dies
        cl.fail_host(a2.node_name)
        rec = cl.recovery.run_once()
        assert "default/ms" in rec.evicted_gangs, rec
        assert "default/ms" not in cl.recovery._degraded
        cl.close()


@__import__("pytest").mark.slow
class TestMultisliceRealDistributed:
    def test_dcn_spanning_gang_consumed_by_jax_distributed(self):
        """VERDICT r4 next-item #7: a DCN-spanning placement actually
        CONSUMED by real multi-process jax.distributed.  Two v4-8
        slices, a 2-pod x 4-chip gang no single slice holds: the
        allocator splits the dp axis across slices, the crishim injects
        per-worker slice identity + one shared coordinator, and the two
        REAL processes form one jax.distributed domain whose dp axis
        spans the slices (the allreduce runs over the simulated DCN)."""
        import json

        from kubegpu_tpu.workloads import specs

        cl = SimCluster(["v4-8", "v4-8"], real_processes=True,
                        extra_env={"JAX_PLATFORMS": "cpu"})
        try:
            pods = [
                tpu_pod(f"msdp-{i}", chips=4,
                        gang=GangSpec(name="msdp", size=2, index=i),
                        mesh_axes={"dp": 2, "tp": 4}, multislice=True,
                        command=specs._prog("allreduce_bench"))
                for i in range(2)
            ]
            cl.submit(*pods)
            codes = cl.run_to_completion(timeout_s=300)
            assert all(codes.get(p.name) == 0 for p in pods), (
                codes,
                [cl.api.get("Pod", p.name).status.message for p in pods])
            # placement: the dp halves landed on DIFFERENT slices
            a0 = pod_allocation(cl.api.get("Pod", "msdp-0"))
            a1 = pod_allocation(cl.api.get("Pod", "msdp-1"))
            assert a0.slice_id != a1.slice_id, "gang did not span slices"
            # injection: each worker saw ITS slice id, one coordinator
            envs = {h.pod_name: h.env for h in cl.runtime.containers()}
            assert envs["msdp-0"]["KUBETPU_SLICE_ID"] == a0.slice_id
            assert envs["msdp-1"]["KUBETPU_SLICE_ID"] == a1.slice_id
            assert envs["msdp-0"]["JAX_COORDINATOR_ADDRESS"] == \
                envs["msdp-1"]["JAX_COORDINATOR_ADDRESS"]
            assert {envs[f"msdp-{i}"]["TPU_WORKER_ID"]
                    for i in range(2)} == {"0", "1"}
            # consumption: the 2-process allreduce really ran over the
            # spanning dp axis (worker 0 printed the bandwidth line)
            out0 = next(h for h in cl.runtime.containers()
                        if h.pod_name == "msdp-0").stdout
            line = json.loads(out0.strip().splitlines()[-1])
            assert line["metric"] == "allreduce_algo_bandwidth"
            assert line["devices"] == 2
            assert line["value"] > 0
        finally:
            cl.close()

"""Bounded in-memory time-series store — the fleet FLIGHT RECORDER
(ISSUE 20).

PR 5's observability stack stopped at point-in-time surfaces: a
``MetricsRegistry`` snapshot is the CURRENT counters/gauges, and a
``Tracer`` export is the span timeline — neither answers "what did
``serve_failover_total`` do over the last 8 ticks", which is exactly
the question burn-rate alerting (``obs/alerts.py``) and the roadmap's
goodput-per-chip frontier ask.  :class:`SeriesStore` closes the gap:

- :meth:`sample` snapshots a registry at an ENGINE TICK into
  fixed-capacity per-series rings: gauges verbatim, counters as
  PER-TICK DELTAS (so windowed sums are rates), histograms as their
  ``_p50``/``_p99`` percentile tracks.  Tick-indexed and wall-free —
  two runs of the same seed produce bit-identical series, the same
  deterministic-twin convention every smoke gate leans on.
- Windowed queries — :meth:`rate`, :meth:`avg`, :meth:`max` over the
  trailing ``window`` ticks — are what the alert engine evaluates.
- Series END with their instance: the store registers a gauge-delete
  hook on the registry, so when the pool's dead-replica harvest
  deletes ``serve_replica_queue_depth_r<i>`` the matching series is
  closed (no further points) instead of flat-lining at its last
  value.
- :meth:`merge_chrome_trace` exports every series as Perfetto COUNTER
  tracks (``ph:"C"``) merged into a ``Tracer.to_chrome_trace`` JSON,
  so one fleet run renders as a single flame+counter timeline in
  ui.perfetto.dev.
"""
from __future__ import annotations

import json
from collections import deque

__all__ = ["SeriesStore"]

#: default ring capacity per series — at one sample per engine tick a
#: smoke run fits whole; a long-lived daemon keeps the trailing window
DEFAULT_CAPACITY = 4096


class SeriesStore:
    """Per-series bounded rings of ``(tick, value)`` keyed by metric
    name, fed by :meth:`sample` from one :class:`MetricsRegistry`."""

    def __init__(self, registry=None, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.registry = registry
        self.capacity = int(capacity)
        self._series: dict[str, deque] = {}
        self._last_counters: dict[str, float] = {}
        self._ended: set[str] = set()
        self.samples = 0
        if registry is not None and hasattr(registry,
                                            "add_gauge_delete_hook"):
            registry.add_gauge_delete_hook(self._on_gauge_delete)

    # -- ingest ---------------------------------------------------------

    def _on_gauge_delete(self, name: str) -> None:
        """Registry callback at the dead-instance choke point: the
        gauge is gone from the scrape surface, so its series is CLOSED
        — it keeps its history but takes no further points."""
        if name in self._series:
            self._ended.add(name)

    def _push(self, name: str, tick: int, value: float) -> None:
        if name in self._ended:
            return
        ring = self._series.get(name)
        if ring is None:
            ring = self._series[name] = deque(maxlen=self.capacity)
        ring.append((tick, float(value)))

    def sample(self, tick: int) -> None:
        """Snapshot the registry at ``tick``: gauges as-is, counters
        as deltas since the previous sample, histograms as p50/p99
        tracks.  Idempotence is NOT assumed — call once per tick.

        This is the recorder's per-tick hot path (the ``cb_obs_fleet``
        bench gates its cost at <= 5% of a twin tick), so the push
        loop is inlined rather than routed through :meth:`_push`."""
        if self.registry is None:
            raise ValueError("SeriesStore built without a registry")
        tick = int(tick)
        snap = self.registry.snapshot()
        series, ended, cap = self._series, self._ended, self.capacity
        for name, v in snap["gauges"].items():
            if name in ended:
                continue
            ring = series.get(name)
            if ring is None:
                ring = series[name] = deque(maxlen=cap)
            ring.append((tick, float(v)))
        if snap["counters"]:
            last_c = self._last_counters
            for name, v in snap["counters"].items():
                last = last_c.get(name, 0.0)
                last_c[name] = v
                self._push(name, tick, v - last)
        for name, h in snap["histograms"].items():
            self._push(name + "_p50", tick, h["p50"])
            self._push(name + "_p99", tick, h["p99"])
        self.samples += 1

    # -- read side ------------------------------------------------------

    def names(self) -> list[str]:
        return sorted(self._series)

    def ended(self, name: str) -> bool:
        return name in self._ended

    def series(self, name: str) -> list[tuple[int, float]]:
        """Full retained ``(tick, value)`` history for one series."""
        return list(self._series.get(name, ()))

    def latest(self, name: str) -> float:
        ring = self._series.get(name)
        return ring[-1][1] if ring else 0.0

    def values(self, name: str, window: int,
               end_tick: int | None = None) -> list[float]:
        """Values in the trailing ``(end - window, end]`` tick window
        (``end`` defaults to the series' newest tick)."""
        ring = self._series.get(name)
        if not ring:
            return []
        end = ring[-1][0] if end_tick is None else int(end_tick)
        lo = end - int(window)
        # ticks are appended in increasing order, so walk from the
        # right and stop at the window edge — O(window), not O(ring)
        out = []
        for t, v in reversed(ring):
            if t > end:
                continue
            if t <= lo:
                break
            out.append(v)
        out.reverse()
        return out

    def rate(self, name: str, window: int,
             end_tick: int | None = None) -> float:
        """Windowed per-tick rate: sum over window / window.  On a
        counter series (stored as deltas) this is the counter's rate;
        on a gauge it is a windowed mean-ish flow."""
        w = max(1, int(window))
        return sum(self.values(name, w, end_tick)) / w

    def avg(self, name: str, window: int,
            end_tick: int | None = None) -> float:
        vals = self.values(name, window, end_tick)
        return sum(vals) / len(vals) if vals else 0.0

    def max(self, name: str, window: int,
            end_tick: int | None = None) -> float:
        vals = self.values(name, window, end_tick)
        return max(vals) if vals else 0.0

    # -- Perfetto export ------------------------------------------------

    def to_counter_events(self, anchor_us: float = 0.0,
                          tick_us: float = 1000.0,
                          pid: int = 1) -> list[dict]:
        """Every series as chrome/Perfetto ``ph:"C"`` counter events,
        one per sample, ticks mapped to ``anchor_us + tick*tick_us``."""
        events: list[dict] = []
        for name in sorted(self._series):
            for t, v in self._series[name]:
                events.append({
                    "ph": "C", "name": name, "pid": pid, "tid": 0,
                    "ts": anchor_us + t * tick_us,
                    "args": {"value": v},
                })
        return events

    def merge_chrome_trace(self, trace_json: str,
                           tick_us: float = 1000.0) -> str:
        """Merge the counter tracks into a ``Tracer.to_chrome_trace``
        export: counters anchor at the earliest span timestamp (so the
        flame and counter timelines line up), events re-sort by ts,
        and the result stays a valid chrome trace
        (``validate_chrome_trace`` accepts ``ph:"C"``)."""
        doc = json.loads(trace_json)
        events = doc.get("traceEvents")
        if not isinstance(events, list):
            raise ValueError("traceEvents missing or not a list")
        anchor = min((e["ts"] for e in events
                      if isinstance(e.get("ts"), (int, float))),
                     default=0.0)
        events.extend(self.to_counter_events(anchor_us=anchor,
                                             tick_us=tick_us))
        events.sort(key=lambda e: e.get("ts", 0.0))
        doc["traceEvents"] = events
        return json.dumps(doc)

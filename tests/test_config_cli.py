"""Config tree (SURVEY.md §6 config row) + kubetpu CLI (user surface)."""

import json
import pathlib

import pytest

from kubegpu_tpu.cli import main, pods_from_spec
from kubegpu_tpu.config import KubeTpuConfig


class TestConfig:
    def test_defaults(self):
        cfg = KubeTpuConfig()
        assert cfg.backend.type == "mock"
        assert cfg.scheduler.locality_weight == 0.6

    def test_file_and_overrides(self, tmp_path):
        p = tmp_path / "cfg.json"
        p.write_text(json.dumps({
            "scheduler": {"locality_weight": 0.7},
            "backend": {"slice_types": ["v5e-16", "v4-8"]},
        }))
        cfg = KubeTpuConfig.load(str(p), overrides=[
            "scheduler.frag_weight=0.2",
            "runtime.real_processes=true",
            "runtime.extra_env=JAX_PLATFORMS:cpu",
        ])
        assert cfg.scheduler.locality_weight == 0.7
        assert cfg.scheduler.frag_weight == 0.2
        assert cfg.backend.slice_types == ["v5e-16", "v4-8"]
        assert cfg.runtime.real_processes is True
        assert cfg.runtime.extra_env == {"JAX_PLATFORMS": "cpu"}

    def test_yaml_file(self, tmp_path):
        p = tmp_path / "cfg.yaml"
        p.write_text("scheduler:\n  fill_weight: 0.1\n")
        assert KubeTpuConfig.load(str(p)).scheduler.fill_weight == 0.1

    def test_unknown_key_rejected(self, tmp_path):
        p = tmp_path / "cfg.json"
        p.write_text(json.dumps({"scheduler": {"nope": 1}}))
        with pytest.raises(ValueError, match="unknown config key"):
            KubeTpuConfig.load(str(p))
        with pytest.raises(ValueError, match="unknown config key"):
            KubeTpuConfig.load(overrides=["scheduler.nope=1"])

    def test_override_of_section_rejected(self):
        """`--set backend=libtpu` must error, not replace the section
        dataclass with a string."""
        with pytest.raises(ValueError, match="config section"):
            KubeTpuConfig.load(overrides=["backend=libtpu"])

    def test_bad_backend_type_rejected(self):
        with pytest.raises(ValueError, match="unknown backend"):
            KubeTpuConfig.load(overrides=["backend.type=cuda"])

    def test_type_mismatch_rejected(self, tmp_path):
        p = tmp_path / "cfg.json"
        p.write_text(json.dumps({"scheduler": {"locality_weight": "high"}}))
        with pytest.raises(ValueError, match="expected float"):
            KubeTpuConfig.load(str(p))

    def test_round_trip(self):
        cfg = KubeTpuConfig.load(overrides=["scheduler.locality_weight=0.9"])
        again = KubeTpuConfig.from_dict(cfg.to_dict())
        assert again.to_dict() == cfg.to_dict()

    def test_cluster_uses_config(self):
        from kubegpu_tpu.cluster import SimCluster
        cfg = KubeTpuConfig.load(overrides=[
            "backend.slice_types=v4-8",
            "scheduler.locality_weight=0.9",
            "scheduler.coordinator_port=9321",
        ])
        cl = SimCluster.from_config(cfg)
        assert cl.scheduler.allocator.locality_weight == 0.9
        assert cl.scheduler.coordinator_port == 9321
        assert len(cl.agents) == 1
        cl.close()


class TestSpecParsing:
    def test_gang_expansion_and_fields(self):
        pods, slices = pods_from_spec({
            "cluster": {"slices": ["v5e-16"]},
            "pods": [
                {"name": "llama", "gang": 4, "chips": 4,
                 "mesh_axes": {"dp": 4, "tp": 4},
                 "command": ["noop"], "env": {"A": "1"}},
                {"name": "frac", "millitpu": 250},
            ],
        })
        assert slices == ["v5e-16"]
        assert [p.name for p in pods] == [
            "llama-0", "llama-1", "llama-2", "llama-3", "frac"]
        assert pods[0].spec.total_chips == 4
        assert pods[4].spec.total_millitpu == 250

    def test_gang_dict_with_name(self):
        pods, _ = pods_from_spec({"pods": [
            {"name": "w", "gang": {"name": "myjob", "size": 2}, "chips": 1},
        ]})
        from kubegpu_tpu.kubemeta.codec import pod_gang_spec
        assert pod_gang_spec(pods[0]).name == "myjob"
        assert pod_gang_spec(pods[1]).index == 1


class TestCli:
    def test_slices_and_configs(self, capsys):
        assert main(["slices"]) == 0
        assert "v5e-64" in capsys.readouterr().out
        assert main(["configs"]) == 0
        assert "config4" in capsys.readouterr().out

    def test_apply_schedule_only_with_top(self, tmp_path, capsys):
        spec = tmp_path / "spec.json"
        spec.write_text(json.dumps({
            "cluster": {"slices": ["v4-8"]},
            "pods": [{"name": "p", "chips": 4,
                      "mesh_axes": {"dp": 4}, "command": ["noop"]}],
        }))
        trace = tmp_path / "trace.json"
        rc = main(["apply", "-f", str(spec), "--schedule-only", "--top",
                   "--trace-out", str(trace)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "Running" in out
        assert "v4-8-slice-0" in out     # occupancy map header
        assert "a a" in out              # gang letters in the map
        events = json.loads(trace.read_text())
        assert any(e["kind"] == "schedule" for e in events)

    def test_bench_verb(self, capsys):
        assert main(["bench", "--gangs", "5"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["metric"] == "gang_schedule_p50_latency"
        assert out["value"] > 0

    def test_demo_dry(self, capsys):
        assert main(["demo", "config5"]) == 0
        out = capsys.readouterr().out
        assert "tenant-b-1" in out and "fill" in out

    def test_apply_runs_workload_to_completion(self, tmp_path, capsys):
        """Real subprocess through the CLI: schedule → inject → run."""
        spec = tmp_path / "spec.json"
        spec.write_text(json.dumps({
            "cluster": {"slices": ["v4-8"]},
            "pods": [{"name": "mnist", "chips": 1,
                      "command": ["python", "-m",
                                  "kubegpu_tpu.workloads.programs.mnist_mlp"],
                      "env": {"KUBETPU_EXPECT_CHIPS": "1"}}],
        }))
        rc = main(["apply", "-f", str(spec), "--real", "--timeout", "120"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "Succeeded" in out


class TestExampleSpecs:
    """Every spec in examples/ must parse, schedule, and (with the fake
    runtime) run its pods to terminal phases — the user-surface contract
    (reference: example/ YAML, SURVEY.md §3)."""

    EXAMPLES = pathlib.Path(__file__).parent.parent / "examples"

    def test_examples_dir_has_all_baseline_configs(self):
        names = {p.name for p in self.EXAMPLES.glob("*.yaml")}
        for want in ("config1", "config2", "config3", "config4", "config5"):
            assert any(n.startswith(want) for n in names), names

    @pytest.mark.parametrize("spec_file", sorted(
        (pathlib.Path(__file__).parent.parent / "examples").glob("*.yaml"),
        key=lambda p: p.name), ids=lambda p: p.name)
    def test_spec_schedules_and_completes(self, spec_file):
        from kubegpu_tpu.cli import load_spec_file, pods_from_spec
        from kubegpu_tpu.cluster import SimCluster
        from kubegpu_tpu.kubemeta import PodPhase

        pods, slices = pods_from_spec(load_spec_file(str(spec_file)))
        assert pods, f"{spec_file.name}: no pods"
        cl = SimCluster(slices)   # FakeRuntime: containers exit 0 on reap
        cl.submit(*pods)
        cl.run_to_completion(timeout_s=30)
        phases = {p.name: p.status.phase for p in cl.api.list("Pod")}
        assert all(ph == PodPhase.SUCCEEDED for ph in phases.values()), phases
        cl.close()

    def test_priority_spec_carries_priority(self):
        from kubegpu_tpu.cli import load_spec_file, pods_from_spec
        pods, _ = pods_from_spec(load_spec_file(
            str(self.EXAMPLES / "priority-preemption.yaml")))
        by_name = {p.name: p for p in pods}
        assert by_name["urgent"].spec.priority == 10
        assert by_name["batch-0"].spec.priority == 0

    def test_metrics_verb(self, tmp_path, capsys):
        spec = tmp_path / "spec.json"
        spec.write_text(json.dumps({
            "cluster": {"slices": ["v4-8"]},
            "pods": [{"name": "p", "chips": 1, "command": ["noop"]}],
        }))
        rc = main(["metrics", "-f", str(spec), "--schedule-only"])
        out = capsys.readouterr().out
        assert rc == 0
        snap = json.loads(out)
        assert snap["histograms"]["schedule_latency_ms"]["count"] >= 1
        rc = main(["metrics", "-f", str(spec), "--schedule-only",
                   "--format", "prometheus"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "# TYPE kubetpu_schedule_latency_ms histogram" in out

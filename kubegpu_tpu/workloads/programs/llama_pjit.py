"""Multi-host Llama pjit training — BASELINE config 4 workload.

Each gang member (one per TPU host) initializes jax.distributed from the
injected env, joins the global mesh, and runs GSPMD-sharded train steps on
a Llama-family model.  Optional orbax checkpointing demonstrates the
gang-reschedule → resume story (SURVEY.md §6 checkpoint/resume).

Env knobs (set via pod spec env):
  LLAMA_PRESET   tiny (default) | 8b
  LLAMA_STEPS    number of train steps (default 3)
  LLAMA_MESH     e.g. "dp:2,tp:2"; defaults to the scheduler-injected
                 KUBETPU_MESH_AXES (the mesh placement was optimized for),
                 else dp over all devices
  LLAMA_CKPT_DIR if set, restore at start / save at end (params AND
                 optimizer state)
  LLAMA_PROFILE_DIR
                 if set, worker 0 captures a jax.profiler trace of the
                 train steps there (view with tensorboard/xprof —
                 SURVEY.md §6 tracing row)
"""

from __future__ import annotations

import json
import os
import sys


def parse_mesh(spec: str | None, n_devices: int) -> dict[str, int]:
    """Mesh axes with graceful degradation: if the requested product
    doesn't match the devices actually present (e.g. the CPU simulation
    gives 1 device/process where real hosts have 4 chips), fold the axes
    down rather than crash — dropping from the front (dp absorbs last)."""
    axes: dict[str, int] = {}
    if spec:
        for part in spec.split(","):
            k, v = part.split(":")
            axes[k.strip()] = int(v)
    elif os.environ.get("KUBETPU_MESH_AXES"):
        axes = {k: int(v)
                for k, v in json.loads(os.environ["KUBETPU_MESH_AXES"])}
    if not axes:
        return {"dp": n_devices}
    prod = 1
    for v in axes.values():
        prod *= v
    if prod == n_devices:
        return axes
    # fold: shrink axes (last-first) until the product fits, then give
    # any remainder to dp
    out = dict(axes)
    for name in reversed(list(out)):
        while out[name] > 1 and prod > n_devices:
            if prod % 2:
                break
            out[name] //= 2
            prod //= 2
    if prod != n_devices:
        out = {"dp": n_devices}
    print(f"llama_pjit: folded mesh {axes} -> {out} "
          f"for {n_devices} devices", file=sys.stderr)
    return out


def main() -> int:
    from kubegpu_tpu.workloads.programs.distributed import init_from_env

    env = init_from_env()
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubegpu_tpu.models import (
        LlamaConfig, llama_init, llama_param_specs,
    )
    from kubegpu_tpu.models.llama import make_train_step
    from kubegpu_tpu.parallel import make_mesh, named_sharding_tree
    from kubegpu_tpu.parallel.sharding import fit_spec

    preset = os.environ.get("LLAMA_PRESET", "tiny")
    steps = int(os.environ.get("LLAMA_STEPS", "3"))
    cfg = (LlamaConfig.llama3_8b() if preset == "8b"
           else LlamaConfig.tiny(n_heads=4, n_kv_heads=4, dtype="float32"))
    axes = parse_mesh(os.environ.get("LLAMA_MESH"), jax.device_count())
    mesh = make_mesh(axes)

    params = llama_init(jax.random.PRNGKey(0), cfg)
    specs = named_sharding_tree(mesh, llama_param_specs(cfg))
    params = jax.device_put(params, specs)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)

    ckpt_dir = os.environ.get("LLAMA_CKPT_DIR")
    start_step = 0
    resumed_opt = False
    ckpt = None
    if ckpt_dir:
        from kubegpu_tpu.ckpt import TrainCheckpointer
        ckpt = TrainCheckpointer(ckpt_dir)
        state, start_step = ckpt.restore_or_init(
            {"params": params, "opt_state": opt_state},
            shardings={"params": specs})
        params, opt_state = state["params"], state["opt_state"]
        resumed_opt = start_step > 0

    step_fn = jax.jit(make_train_step(cfg, opt, mesh),
                      donate_argnums=(0, 1))
    batch = max(2, axes.get("dp", 1) * axes.get("fsdp", 1))
    seq = 32  # all-T loss contract: tokens are [B, T], T tile-aligned
    tok_sharding = NamedSharding(mesh, fit_spec(mesh, P(("dp", "fsdp"),
                                                        None)))
    profile_dir = os.environ.get("LLAMA_PROFILE_DIR")
    profiling = bool(profile_dir) and env.worker_id == 0
    if profiling:
        jax.profiler.start_trace(profile_dir)
    losses = []
    try:
        for i in range(start_step, start_step + steps):
            tokens = (np.arange(batch * seq, dtype=np.int32)
                      .reshape(batch, seq) * (i + 3)) % cfg.vocab_size
            tokens = jax.device_put(jnp.asarray(tokens), tok_sharding)
            with jax.profiler.StepTraceAnnotation("train", step_num=i):
                params, opt_state, loss = step_fn(params, opt_state, tokens)
            losses.append(float(loss))
    finally:
        if profiling:
            jax.profiler.stop_trace()

    if ckpt is not None:
        ckpt.save(start_step + steps - 1,
                  {"params": params, "opt_state": opt_state})
        ckpt.wait()

    if env.worker_id == 0:
        print(f"llama_pjit: preset={preset} mesh={axes} "
              f"workers={env.num_workers} devices={jax.device_count()} "
              f"start_step={start_step} resumed_opt={resumed_opt} "
              f"losses={[round(l, 4) for l in losses]}")
    if not all(np.isfinite(losses)):
        print("FAIL: non-finite loss", file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())

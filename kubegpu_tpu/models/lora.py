"""LoRA adapters for the Llama family (TPU-native addition).

Parameter-efficient fine-tuning: frozen base weights + trainable
low-rank deltas ``w_eff = w + (alpha/rank) * a @ b`` on selected matmul
weights.  Fits the house design the same way int8 serving does — the
model code only uses weights via ``@``, so training traces
:func:`lora_merge` (the a@b delta is tiny: [in,r]@[r,out], XLA fuses
it) and the existing forward/loss run UNCHANGED on the merged tree,
while :func:`make_lora_train_step` differentiates and updates ONLY the
adapters.  On a gang, adapters shard like their base weights'
non-contracted dims (a on fsdp, b on tp), so tp/fsdp training works
with no new collectives.

Memory story (why LoRA on TPU): optimizer moments exist only for the
adapters — for the 618M-param bench config at rank 8 on wq/wv that is
~0.3% of the adamw state, the difference between fitting and OOM when
fine-tuning bigger-than-bench models in 16 GiB.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# the classic attention-only default (LoRA paper: q and v projections)
DEFAULT_TARGETS = ("wq", "wv")
# every stacked matmul weight that CAN take an adapter
ADAPTABLE = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


@dataclass(frozen=True)
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: tuple[str, ...] = DEFAULT_TARGETS

    def __post_init__(self) -> None:
        if self.rank < 1:
            raise ValueError(f"rank must be >= 1, got {self.rank}")
        bad = set(self.targets) - set(ADAPTABLE)
        if bad:
            raise ValueError(f"unknown LoRA targets {sorted(bad)}; "
                             f"adaptable: {ADAPTABLE}")

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank


def lora_init(key: jax.Array, params: dict, lcfg: LoRAConfig) -> dict:
    """Adapters for the targeted stacked-layer weights: per target,
    ``a`` [L, in, r] (gaussian / sqrt(in)) and ``b`` [L, r, out]
    (zeros) — so the initial delta is exactly zero and step 0 of
    fine-tuning IS the base model."""
    out = {}
    keys = jax.random.split(key, len(lcfg.targets))
    for k, name in zip(keys, lcfg.targets):
        w = params["layers"][name]           # [L, in, out]
        ell, d_in, d_out = w.shape
        out[name] = {
            "a": (jax.random.normal(k, (ell, d_in, lcfg.rank),
                                    jnp.float32)
                  * (d_in ** -0.5)).astype(w.dtype),
            "b": jnp.zeros((ell, lcfg.rank, d_out), w.dtype),
        }
    return out


# each adaptable weight's (input-dim, output-dim) mesh axes, mirroring
# llama_param_specs: the down/out projections are transposed (megatron
# row-parallel), so their adapters must shard the SAME axes as the base
# or XLA inserts per-step resharding collectives around the merge
_IN_OUT_AXES = {
    "wq": ("fsdp", "tp"), "wk": ("fsdp", "tp"), "wv": ("fsdp", "tp"),
    "w_gate": ("fsdp", "tp"), "w_up": ("fsdp", "tp"),
    "wo": ("tp", "fsdp"), "w_down": ("tp", "fsdp"),
}


def lora_param_specs(lcfg: LoRAConfig) -> dict:
    """GSPMD specs: ``a`` shards its input dim and ``b`` its output dim
    on the SAME axes the base weight uses for those dims (transposed
    for the row-parallel wo/w_down) — the rank dim replicates."""
    out = {}
    for name in lcfg.targets:
        ax_in, ax_out = _IN_OUT_AXES[name]
        out[name] = {"a": P(None, ax_in, None),
                     "b": P(None, None, ax_out)}
    return out


def lora_merge(params: dict, adapters: dict, lcfg: LoRAConfig) -> dict:
    """Base tree with targeted weights replaced by w + scale * a@b —
    trace this inside the loss (cheap) or call once to bake adapters in
    for serving (the merged tree drops into decode/quantize unchanged)."""
    layers = dict(params["layers"])
    for name, ab in adapters.items():
        delta = jnp.einsum("lir,lro->lio", ab["a"], ab["b"])
        layers[name] = params["layers"][name] \
            + (lcfg.scaling * delta).astype(params["layers"][name].dtype)
    return {**params, "layers": layers}


def lora_n_params(adapters: dict) -> int:
    return sum(x.size for x in jax.tree.leaves(adapters))


def make_lora_train_step(cfg, lcfg: LoRAConfig, optimizer,
                         mesh=None, loss_fn=None):
    """(adapters, opt_state, base_params, tokens) →
    (adapters, opt_state, loss): grads flow ONLY to the adapters; base
    params pass through untouched (freeze by construction, not by
    masking).  ``loss_fn`` defaults to the Llama next-token loss."""
    import optax

    from kubegpu_tpu.models.llama import next_token_loss

    loss_fn = loss_fn if loss_fn is not None else next_token_loss

    def adapter_loss(adapters, base_params, tokens):
        merged = lora_merge(base_params, adapters, lcfg)
        return loss_fn(merged, tokens, cfg, mesh)

    def step(adapters, opt_state, base_params, tokens):
        loss, grads = jax.value_and_grad(adapter_loss)(
            adapters, base_params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, adapters)
        adapters = optax.apply_updates(adapters, updates)
        return adapters, opt_state, loss

    return step

"""ICI-link locality scoring: the ≥90% north-star metric.

The reference scored allocations by how few NVLink groups they spanned
(SURVEY.md §3 ``gpuschedulerplugin`` "topology-scoring": prefer fewest
groups / most NVLink-adjacent).  The honest TPU equivalent (SURVEY.md §8
"Honest locality measurement") scores the *actual collective traffic* a
workload's sharding implies: we derive the chip-pair traffic set from the
logical mesh axes (dp/fsdp/tp/sp rings) mapped onto the allocated physical
coords, then measure the fraction of traffic pairs that ride ICI links
rather than multi-hop or DCN paths.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubegpu_tpu.topology.mesh import Coord, TpuTopology

# Default relative collective volume per parallelism axis, used when the
# workload doesn't declare weights.  Proportional to bytes moved per
# training step in a sharded transformer: tp allreduces activations every
# layer (dominant); sp/cp ring-exchange KV blocks every layer; fsdp
# all-gathers params per layer; ep all-to-alls per MoE layer; dp syncs
# grads once per step.  This is what makes the locality figure "honest"
# (SURVEY.md §8): a dead dp hop costs far less than a dead tp hop, and the
# score reflects that.
DEFAULT_AXIS_WEIGHTS = {
    "tp": 8.0,
    "sp": 4.0,
    "cp": 4.0,
    "ep": 2.0,
    "fsdp": 2.0,
    "dp": 1.0,
}

# SERVING traffic is a different shape from training: a tensor-parallel
# decode engine psums activations over tp on EVERY layer of EVERY
# stride-amortized decode step (latency-critical — it sits on the
# token feedback path), while serving "dp" is independent engine
# replicas behind one admission queue — NO collective ever crosses a
# replica boundary, so a dp hop over a dead link or even DCN costs
# (almost) nothing.  Near-zero rather than zero: keeping replicas near
# each other still helps prefix-cache-affinity routing and shared
# model-load traffic, and a zero weight would make the locality score
# 0/0-degenerate for dp-only serving gangs.
SERVING_AXIS_WEIGHTS = {
    "tp": 8.0,
    "dp": 0.05,
}

# Role-split (disaggregated) serving: a PREFILL replica is a
# throughput-bound batch engine off the token feedback path — its tp
# collective rides large prefill activations where link time hides
# behind compute, so tight tp placement matters less than for a
# DECODE replica, whose per-token psum latency IS the user-visible
# token time.  Decode keeps the default serving weights.
PREFILL_ROLE_TP_WEIGHT = 4.0


def serving_axis_weights(axis_sizes: dict[str, int],
                         role: str | None = None) -> dict[str, float]:
    """Axis weights for a SERVING gang (see SERVING_AXIS_WEIGHTS):
    tp collectives dominate, replica axes are nearly free.  ``role``
    ("prefill" | "decode" | None) adjusts the tp weight for
    disaggregated gangs — prefill tolerates looser tp placement."""
    w = {k: SERVING_AXIS_WEIGHTS.get(k, 1.0) for k in axis_sizes}
    if role == "prefill" and "tp" in w:
        w["tp"] = PREFILL_ROLE_TP_WEIGHT
    return w


def resolve_axis_weights(
    axis_sizes: dict[str, int],
    axis_weights: dict[str, float] | None,
) -> dict[str, float]:
    """Explicit weights win; otherwise look up by conventional axis name
    (unknown names weigh 1.0)."""
    if axis_weights is not None:
        return axis_weights
    return {k: DEFAULT_AXIS_WEIGHTS.get(k, 1.0) for k in axis_sizes}


@dataclass
class TrafficModel:
    """Chip-pair traffic implied by a workload's parallelism strategy.

    ``pairs`` maps (chip_a, chip_b) → relative traffic weight.  XLA lowers
    allreduce/reduce-scatter/all-gather on a mesh axis to ring collectives
    over that axis, so each parallel axis contributes ring-neighbor pairs;
    ring attention / context parallelism contributes the same ring pairs on
    the sequence axis (ppermute neighbor exchange).
    """

    pairs: dict[tuple[Coord, Coord], float] = field(default_factory=dict)

    def add(self, a: Coord, b: Coord, weight: float = 1.0) -> None:
        if a == b:
            return
        key = (min(a, b), max(a, b))
        self.pairs[key] = self.pairs.get(key, 0.0) + weight


def ring_order_for_axis(coords: list[Coord], axis_size: int) -> list[list[Coord]]:
    """Split an ordered coord list into rings of ``axis_size``.

    ``coords`` must be in the logical-device order the workload uses
    (row-major placement order, matching mesh axis layout): consecutive
    chunks of ``axis_size`` form the fastest-varying logical axis.
    """
    assert len(coords) % axis_size == 0
    return [coords[i:i + axis_size] for i in range(0, len(coords), axis_size)]


def traffic_pairs_for_mesh_axes(
    coords: list[Coord],
    axis_sizes: dict[str, int],
    axis_weights: dict[str, float] | None = None,
) -> TrafficModel:
    """Traffic pairs for a logical mesh over ``coords``.

    ``axis_sizes`` is ordered (python dicts preserve order): the *last* axis
    varies fastest over ``coords`` — matching ``jax.sharding.Mesh`` device
    array semantics where ``mesh.devices.reshape(sizes)`` is row-major.
    Each axis of size s contributes ring pairs (i, i+1 mod s) within every
    group that varies only along that axis.

    ``axis_weights`` lets callers weight axes by collective volume (e.g.
    tp allreduce per-layer traffic >> dp gradient sync) — defaults to 1.0.
    """
    sizes = list(axis_sizes.values())
    names = list(axis_sizes.keys())
    total = 1
    for s in sizes:
        total *= s
    if total != len(coords):
        raise ValueError(f"mesh axes {axis_sizes} ≠ {len(coords)} chips")
    weights = resolve_axis_weights(axis_sizes, axis_weights)
    tm = TrafficModel()
    # strides for row-major logical indexing
    strides = [1] * len(sizes)
    for i in range(len(sizes) - 2, -1, -1):
        strides[i] = strides[i + 1] * sizes[i + 1]

    def logical_to_coord(idx: int) -> Coord:
        return coords[idx]

    for ax, (name, s) in enumerate(zip(names, sizes)):
        if s == 1:
            continue
        w = weights.get(name, 1.0)
        stride = strides[ax]
        # enumerate all groups varying only along axis `ax`
        for base in range(total):
            # base must have axis-ax digit 0
            if (base // stride) % s != 0:
                continue
            ring = [logical_to_coord(base + k * stride) for k in range(s)]
            for k in range(s):
                a, b = ring[k], ring[(k + 1) % s]
                if s == 2 and k == 1:
                    break  # 2-ring has one unique pair
                tm.add(a, b, w)
    return tm


def ici_locality(topo: TpuTopology, tm: TrafficModel,
                 bad_links: set[tuple[Coord, Coord]] | None = None) -> float:
    """Weighted fraction of traffic pairs that are single-hop ICI links.

    1.0 = every collective neighbor exchange rides a direct ICI link;
    the north-star demands ≥0.90 for the Llama-3-8B pjit gang on v5e-64
    (BASELINE.md).  Pairs between chips on different meshes (no coord in
    ``topo``) count as DCN (non-local).  A pair riding a link in
    ``bad_links`` (normalized (min,max) coord pairs) is non-local: traffic
    must detour around the dead link.
    """
    if not tm.pairs:
        return 1.0
    total = 0.0
    local = 0.0
    for (a, b), w in tm.pairs.items():
        total += w
        if (topo.has_coord(a) and topo.has_coord(b)
                and topo.are_ici_adjacent(a, b)
                and not (bad_links and (min(a, b), max(a, b)) in bad_links)):
            local += w
    return local / total


def mean_hop_distance(topo: TpuTopology, tm: TrafficModel) -> float:
    """Average torus hop distance per unit traffic — a finer-grained tie-
    breaker than the binary locality fraction (1.0 is optimal)."""
    if not tm.pairs:
        return 0.0
    total_w = sum(tm.pairs.values())
    return sum(
        topo.hop_distance(a, b) * w for (a, b), w in tm.pairs.items()
    ) / total_w

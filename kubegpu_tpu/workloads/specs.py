"""The five BASELINE.json acceptance configs as submittable pod sets —
the user surface (reference: ``example/`` YAML applied with kubectl,
SURVEY.md §3 "Example workloads").

Each builder returns (pods, expected_cluster) so tests/CLI can submit the
workload to a ``SimCluster`` of the right slice types and watch it run
end-to-end through schedule → inject → execute.
"""

from __future__ import annotations

import sys

from kubegpu_tpu.cluster import tpu_pod
from kubegpu_tpu.kubemeta import GangSpec, Pod

PY = sys.executable or "python"


def _prog(module: str) -> list[str]:
    return [PY, "-m", f"kubegpu_tpu.workloads.programs.{module}"]


def config1_cpu_mnist() -> tuple[list[Pod], list[str]]:
    """Single-pod torch-MNIST, 0-device request (CPU fallback path)."""
    return [tpu_pod("mnist-cpu", chips=0, command=_prog("mnist_torch"))], \
        ["v4-8"]


def config2_resnet_1chip() -> tuple[list[Pod], list[str]]:
    """Single-pod JAX ResNet requesting 1 TPU chip."""
    return [tpu_pod("resnet-1chip", chips=1,
                    command=_prog("resnet_single"),
                    env={"KUBETPU_EXPECT_CHIPS": "1"})], ["v4-8"]


def config3_dp_gang(steps: int = 2) -> tuple[list[Pod], list[str]]:
    """4-pod data-parallel gang on one v4-8 host (intra-host ICI)."""
    pods = [
        tpu_pod(f"dp-{i}", chips=1,
                gang=GangSpec(name="dp-mnist", size=4, index=i),
                mesh_axes={"dp": 4},
                command=_prog("llama_pjit"),
                env={"LLAMA_STEPS": str(steps)})
        for i in range(4)
    ]
    return pods, ["v4-8"]


def config4_llama_v5e16(steps: int = 2) -> tuple[list[Pod], list[str]]:
    """Multi-host JAX pjit Llama on v5e-16 (4 hosts × 4 chips, dp×tp)."""
    pods = [
        tpu_pod(f"llama-{i}", chips=4,
                gang=GangSpec(name="llama-8b", size=4, index=i),
                mesh_axes={"dp": 4, "tp": 4},
                # Llama-3-8B sharded 4-way tp: ~4 GiB weights + optimizer
                # + activations per chip — any v5e chip (16 GiB) clears it;
                # declared so HBM-aware admission is exercised end-to-end
                hbm_gib=8.0,
                command=_prog("llama_pjit"),
                env={"LLAMA_STEPS": str(steps)})
        for i in range(4)
    ]
    return pods, ["v5e-16"]


def config5_multitenant() -> tuple[list[Pod], list[str]]:
    """Two co-tenant jobs: fractional-chip pods + a slice gang
    (bin-packing)."""
    pods = [
        tpu_pod("tenant-a-frac", millitpu=400,
                command=_prog("resnet_single")),
        tpu_pod("tenant-a-frac2", millitpu=500,
                command=_prog("resnet_single")),
    ]
    pods += [
        tpu_pod(f"tenant-b-{i}", chips=4,
                gang=GangSpec(name="tenant-b", size=2, index=i),
                mesh_axes={"dp": 2, "tp": 4},
                command=_prog("llama_pjit"),
                env={"LLAMA_STEPS": "2"})
        for i in range(2)
    ]
    return pods, ["v5e-16"]


def allreduce_gang(n_pods: int = 4,
                   slice_type: str = "v4-8") -> tuple[list[Pod], list[str]]:
    """The ICI-allreduce microbenchmark gang (north-star metric #2)."""
    pods = [
        tpu_pod(f"allreduce-{i}", chips=1,
                gang=GangSpec(name="allreduce", size=n_pods, index=i),
                mesh_axes={"dp": n_pods},
                command=_prog("allreduce_bench"))
        for i in range(n_pods)
    ]
    return pods, [slice_type]


def t5_seq2seq(slice_type: str = "v4-8") -> tuple[list[Pod], list[str]]:
    """Encoder-decoder family on one chip (the seq2seq counterpart of
    config2's single-chip training)."""
    pods = [tpu_pod("t5", chips=1, command=_prog("t5_train"),
                    env={"T5_STEPS": "3"})]
    return pods, [slice_type]


def llama_serving(slice_type: str = "v4-8") -> tuple[list[Pod], list[str]]:
    """Serving as a schedulable workload: a 1-chip pod runs KV-cache
    decode and reports its tokens/s as a harvestable metric line."""
    pods = [tpu_pod("llama-serve", chips=1, command=_prog("llama_serve"),
                    env={"SERVE_STEPS": "16"},
                    workload="serving")]
    return pods, [slice_type]


def tp_serving(tp: int = 4, dp: int = 1,
               slice_type: str = "v5e-16") -> tuple[list[Pod], list[str]]:
    """MULTI-CHIP serving: one pod asks for a dp x tp chip block and
    runs the mesh-sharded continuous-batching engine (page pool split
    over KV heads across the tp ring, dp independent replicas behind
    one queue).  The gang request carries the tp degree in its mesh
    axes AND the serving workload kind, so topology scoring sees a
    serving slice: contiguous ICI goes to the tp ring, replica
    adjacency is nearly free."""
    pods = [tpu_pod(
        "tp-serve", chips=dp * tp,
        mesh_axes={"dp": dp, "tp": tp},
        workload="serving",
        command=_prog("llama_serve"),
        env={"SERVE_MODE": "continuous", "SERVE_TP": str(tp),
             "SERVE_DP": str(dp), "SERVE_STEPS": "16"})]
    return pods, [slice_type]


ALL_CONFIGS = {
    "config1": config1_cpu_mnist,
    "config2": config2_resnet_1chip,
    "config3": config3_dp_gang,
    "config4": config4_llama_v5e16,
    "config5": config5_multitenant,
    "allreduce": allreduce_gang,
    "t5": t5_seq2seq,
    "serve": llama_serving,
    "tp_serve": tp_serving,
}

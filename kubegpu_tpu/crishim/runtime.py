"""Container runtimes behind the shim.

The reference forwarded rewritten CRI calls to dockershim/containerd
(SURVEY.md §4.3); in this environment the "real runtime" launches workload
subprocesses with the injected env — real JAX programs consume the
injection exactly as a containerized workload would (SURVEY.md §5 (d)).
"""

from __future__ import annotations

import os
import subprocess
import threading
from dataclasses import dataclass, field


@dataclass
class ContainerHandle:
    pod_name: str
    container_name: str
    env: dict[str, str]
    command: list[str]
    pid: int | None = None
    exit_code: int | None = None
    stdout: str = ""
    stderr: str = ""
    _proc: subprocess.Popen | None = field(default=None, repr=False)

    def running(self) -> bool:
        """Liveness without collecting output (CRI ListContainers)."""
        if self.exit_code is not None:
            return False
        return self._proc is not None and self._proc.poll() is None

    def wait(self, timeout: float | None = None) -> int | None:
        if self._proc is not None:
            try:
                out, err = self._proc.communicate(timeout=timeout)
                self.stdout, self.stderr = out, err
                self.exit_code = self._proc.returncode
            except subprocess.TimeoutExpired:
                return None
        return self.exit_code

    def kill(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()
            self.wait(timeout=10)


class ContainerRuntime:
    """CRI RuntimeService equivalent (create/list/remove)."""

    def create_container(self, pod_name: str, container_name: str,
                         command: list[str],
                         env: dict[str, str]) -> ContainerHandle:
        raise NotImplementedError

    def containers(self) -> list[ContainerHandle]:
        raise NotImplementedError


class FakeRuntime(ContainerRuntime):
    """Records creations; never launches.  Exit code settable by tests."""

    def __init__(self) -> None:
        self.created: list[ContainerHandle] = []

    def create_container(self, pod_name, container_name, command, env):
        h = ContainerHandle(pod_name=pod_name, container_name=container_name,
                            env=dict(env), command=list(command), exit_code=0)
        self.created.append(h)
        return h

    def containers(self) -> list[ContainerHandle]:
        return list(self.created)


class SubprocessRuntime(ContainerRuntime):
    """Launches workload processes with the injected env.

    The child inherits a *minimal* base env (PATH, PYTHONPATH, HOME) plus
    the injection — mirroring a container's clean env — with optional
    ``extra_env`` for test plumbing (e.g. forcing JAX_PLATFORMS=cpu).
    """

    def __init__(self, extra_env: dict[str, str] | None = None,
                 inherit: tuple[str, ...] = ("PATH", "HOME", "PYTHONPATH",
                                             "TMPDIR", "LANG")):
        self.extra_env = extra_env or {}
        self.inherit = inherit
        self._lock = threading.Lock()
        self._containers: list[ContainerHandle] = []

    def create_container(self, pod_name, container_name, command, env):
        base = {k: os.environ[k] for k in self.inherit if k in os.environ}
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        base["PYTHONPATH"] = (
            repo_root + os.pathsep + base.get("PYTHONPATH", "")).rstrip(os.pathsep)
        full_env = {**base, **self.extra_env, **env}
        proc = subprocess.Popen(
            command, env=full_env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        h = ContainerHandle(pod_name=pod_name, container_name=container_name,
                            env=full_env, command=list(command),
                            pid=proc.pid, _proc=proc)
        with self._lock:
            self._containers.append(h)
        return h

    def containers(self) -> list[ContainerHandle]:
        with self._lock:
            return list(self._containers)

"""Real gRPC CRI endpoint — the reference's actual transport.

The reference's crishim was "a real gRPC server implementing the
kubelet CRI" (SURVEY.md §2 L2, §4.3); through r3 this repo's wire was
length-prefixed JSON frames with CRI method names.  This module puts a
genuine gRPC server (grpcio, HTTP/2 over a unix socket) in front of the
same :class:`~kubegpu_tpu.crishim.criserver.CriVerbs` core, exposing
the kubelet CRI's service/method names:

    /runtime.v1.RuntimeService/{Version, CreateContainer,
        StartContainer, StopContainer, RemoveContainer, ListContainers,
        ContainerStatus}
    /runtime.v1.ImageService/{PullImage, ImageStatus, ListImages,
        RemoveImage, ImageFsInfo}

both registered on ONE endpoint, as kubelet expects
(``--container-runtime-endpoint`` + ``--image-service-endpoint`` point
at the same socket).

Message encoding is hand-rolled JSON bytes rather than the CRI
protobufs — protoc is not available in this environment, and grpc's
generic method handlers accept any (de)serializer (VERDICT r3 next-item
#5 explicitly scoped it this way).  Honest parity note: a stock kubelet
speaks protobuf message bodies, so it could exchange *frames* with this
server but not *messages*; swapping the two serializer callables for
protobuf ones (once protoc-generated code exists) is the entire
remaining gap — service names, method routing, status codes, deadline
and cancellation semantics are the real thing.  The JSON-frame
:class:`CriServer` remains as the dependency-free fallback; both
transports dispatch into one `CriVerbs`, so they cannot diverge.
"""

from __future__ import annotations

import json
from concurrent import futures

import grpc

from kubegpu_tpu.crishim.criserver import (
    CriError,
    CriVerbs,
    RemoteCriShim,
)
from kubegpu_tpu.obs import get_logger

log = get_logger("crigrpc")

RUNTIME_SERVICE = "runtime.v1.RuntimeService"
IMAGE_SERVICE = "runtime.v1.ImageService"

SERVICE_METHODS = {
    RUNTIME_SERVICE: (
        "Version", "CreateContainer", "StartContainer", "StopContainer",
        "RemoveContainer", "ListContainers", "ContainerStatus",
    ),
    IMAGE_SERVICE: (
        "PullImage", "ImageStatus", "ListImages", "RemoveImage",
        "ImageFsInfo",
    ),
}

_METHOD_SERVICE = {m: s for s, ms in SERVICE_METHODS.items() for m in ms}


def _encode(obj: dict) -> bytes:
    return json.dumps(obj).encode()


def _decode(data: bytes) -> dict:
    return json.loads(data or b"{}")


class GrpcCriServer(CriVerbs):
    """gRPC transport over the shared CRI verb core.  Same constructor
    as :class:`CriServer`; ``start()`` binds ``unix:<socket_path>``."""

    def start(self) -> "GrpcCriServer":
        self._grpc = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="cri-grpc"))

        def make_handler(method: str):
            def unary(request: bytes, context: grpc.ServicerContext):
                try:
                    return _encode(self._dispatch(method,
                                                  _decode(request)))
                except CriError as e:
                    context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                  str(e))
                except Exception as e:   # noqa: BLE001 — carried as status
                    context.abort(grpc.StatusCode.INTERNAL,
                                  f"{type(e).__name__}: {e}")
            return grpc.unary_unary_rpc_method_handler(unary)

        for service, methods in SERVICE_METHODS.items():
            self._grpc.add_generic_rpc_handlers((
                grpc.method_handlers_generic_handler(
                    service, {m: make_handler(m) for m in methods}),))
        self._grpc.add_insecure_port(f"unix:{self.socket_path}")
        self._grpc.start()
        log.info("grpc listening", socket=self.socket_path,
                 node=self.node_name)
        return self

    def close(self) -> None:
        self._grpc.stop(grace=2).wait(timeout=5)
        self._cleanup_socket()


class GrpcCriClient:
    """gRPC counterpart of :class:`CriClient` — same ``call(method,
    request) -> dict`` surface, so :class:`RemoteCriShim` and the
    remote container handles work over either transport unchanged.
    Errors come back as gRPC status codes and re-raise as CriError."""

    def __init__(self, socket_path: str, connect_timeout: float = 5.0):
        self.socket_path = socket_path
        self._channel = grpc.insecure_channel(f"unix:{socket_path}")
        grpc.channel_ready_future(self._channel).result(
            timeout=connect_timeout)
        self._stubs = {
            m: self._channel.unary_unary(f"/{s}/{m}")
            for m, s in _METHOD_SERVICE.items()
        }

    def call(self, method: str, request: dict | None = None) -> dict:
        stub = self._stubs.get(method)
        if stub is None:
            raise CriError(f"unknown method {method!r}")
        try:
            return _decode(stub(_encode(request or {})))
        except grpc.RpcError as e:
            if e.code() in (grpc.StatusCode.FAILED_PRECONDITION,
                            grpc.StatusCode.INTERNAL):
                raise CriError(e.details()) from None
            raise ConnectionError(
                f"CRI gRPC call {method} failed: {e.code().name} "
                f"{e.details()}") from None

    def close(self) -> None:
        self._channel.close()


class GrpcRemoteCriShim(RemoteCriShim):
    """RemoteCriShim over the gRPC endpoint (kubelet's seam, real
    transport).  Identical call sequence: PullImage → CreateContainer →
    StartContainer, then status polling via the shared handle class."""

    def __init__(self, socket_path: str):
        self.client = GrpcCriClient(socket_path)
        self.runtime_name = self.client.call("Version")["runtime_name"]

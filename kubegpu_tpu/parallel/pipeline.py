"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` mesh axis.

The reference has no parallelism code (SURVEY.md §3 — it *places* jobs);
this module extends KubeTPU's TPU-native workload layer so gangs can use
all of dp/tp/pp/sp/ep on their allocated slice.  Design is the SPMD
"collective pipeline" of the scaling-book lineage, not a multi-program
schedule:

- the stacked-layer Llama params shard their leading ``[L, ...]`` dim on
  ``pp`` — each stage holds ``L/S`` contiguous layers, so placement is
  expressed purely as sharding (idiomatic GSPMD), and the stage body is
  the same ``lax.scan`` the single-chip model runs;
- microbatches stream through stages inside one ``lax.scan`` over
  ``M + S - 1`` ticks; stage hand-off is a single ``ppermute`` to the next
  ``pp`` rank (ICI neighbor traffic — the same pattern the allocator's
  ring ordering optimizes);
- tensor parallelism composes *inside* the stage via manual megatron
  collectives (heads/ffn sharded on ``tp``, one ``psum`` after ``wo`` and
  one after ``w_down``) because the stage body runs under ``shard_map``
  where GSPMD constraints don't apply;
- everything is differentiable (``scan`` + ``ppermute`` transpose), so
  ``jax.grad`` of the pipelined loss gives the GPipe backward schedule
  for free — no hand-written backward pass.

Embedding and the LM head run replicated on every pp rank (stage 0
consumes the embedding, the last stage the head); at 8B scale these would
shard on tp/fsdp, which composes the same way.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kubegpu_tpu.ops.flash_attention import xla_attention
from kubegpu_tpu.parallel.sharding import fit_spec

# NB: kubegpu_tpu.models.llama imports this package's sharding module, so
# model-layer imports here must stay function-local to avoid a cycle.


def spmd_pipeline(stage_fn, inputs_mb: jax.Array, n_stages: int,
                  axis_name: str = "pp", remat: bool = False) -> jax.Array:
    """Run the GPipe schedule under ``shard_map``.

    ``inputs_mb`` is ``[M, ...]`` (M microbatches), identical on every
    ``pp`` rank; ``stage_fn(x)`` applies this rank's stage to one
    microbatch activation; ``n_stages`` is the (static) ``pp`` axis size.
    Returns ``[M, ...]`` outputs that are valid on the LAST stage only
    (zeros elsewhere — mask or ``psum`` to use them).

    Tick ``t`` has stage ``s`` processing microbatch ``t - s``; ticks a
    stage is idle for (pipeline bubble) compute garbage that the validity
    select keeps out of both outputs and gradients.
    """
    stage = lax.axis_index(axis_name)
    m = inputs_mb.shape[0]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    body_fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def tick(carry, t):
        act, outs = carry
        x = jnp.where(stage == 0, inputs_mb[jnp.clip(t, 0, m - 1)], act)
        y = body_fn(x)
        oidx = t - (n_stages - 1)
        upd = lax.dynamic_update_index_in_dim(
            outs, y, jnp.clip(oidx, 0, m - 1), 0)
        valid = (stage == n_stages - 1) & (oidx >= 0) & (oidx < m)
        outs = jnp.where(valid, upd, outs)
        act = lax.ppermute(y, axis_name, perm)
        return (act, outs), None

    zero = jnp.zeros(inputs_mb.shape[1:], inputs_mb.dtype)
    outs0 = jnp.zeros_like(inputs_mb)
    (_, outs), _ = lax.scan(
        tick, (zero, outs0), jnp.arange(m + n_stages - 1))
    return outs


# ---------------------------------------------------------------------------
# Llama over (dp, pp, tp)
# ---------------------------------------------------------------------------

def llama_pp_param_specs(cfg) -> dict:
    """PartitionSpec tree for the pipelined Llama: stacked-layer leading
    dim on ``pp`` (contiguous L/S layers per stage), megatron ``tp`` on
    head/ffn dims, embed/head replicated (see module docstring)."""
    return {
        "embed": P(None, None),
        "layers": {
            "attn_norm": P("pp", None),
            "wq": P("pp", None, "tp"),
            "wk": P("pp", None, "tp"),
            "wv": P("pp", None, "tp"),
            "wo": P("pp", "tp", None),
            "mlp_norm": P("pp", None),
            "w_gate": P("pp", None, "tp"),
            "w_up": P("pp", None, "tp"),
            "w_down": P("pp", "tp", None),
        },
        "final_norm": P(None),
        "lm_head": P(None, None),
    }


def _megatron_layer(x: jax.Array, lp: dict, positions: jax.Array,
                    cfg, tp_axis: str | None) -> jax.Array:
    """One decoder layer on tp-local shards: heads/ffn columns are local,
    row-parallel matmuls produce partials resolved by one psum each."""
    from kubegpu_tpu.models.llama import _rmsnorm, _rope

    b, t = x.shape[0], x.shape[1]
    hd = cfg.head_dim
    h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ lp["wq"]).reshape(b, t, -1, hd)
    k = (h @ lp["wk"]).reshape(b, t, -1, hd)
    v = (h @ lp["wv"]).reshape(b, t, -1, hd)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    o = xla_attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                      v.transpose(0, 2, 1, 3), causal=True)
    o = o.transpose(0, 2, 1, 3).reshape(b, t, -1)
    attn = o @ lp["wo"]
    if tp_axis is not None:
        attn = lax.psum(attn, tp_axis)
    x = x + attn.astype(x.dtype)
    h = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
    up = jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])
    down = up @ lp["w_down"]
    if tp_axis is not None:
        down = lax.psum(down, tp_axis)
    return x + down.astype(x.dtype)


def make_pp_loss(cfg: LlamaConfig, mesh: Mesh, n_microbatches: int):
    """Build ``loss(params, tokens)``: the pipelined next-token loss over
    ``mesh`` (axes ⊆ {dp, pp, tp}), jit-ready.  Matches
    :func:`kubegpu_tpu.models.llama.next_token_loss` numerically when the
    microbatch split is even (same per-token mean).
    """
    from kubegpu_tpu.models.llama import _rmsnorm

    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if "pp" in axes:
        pp = axes["pp"]
    else:
        raise ValueError(
            f"mesh {axes} has no 'pp' axis (size-1 is fine)")
    tp = axes.get("tp", 1)
    tp_axis = "tp" if tp > 1 else None
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers {cfg.n_layers} % pp {pp} != 0")
    if cfg.n_heads % tp or cfg.n_kv_heads % tp:
        raise ValueError(
            f"heads ({cfg.n_heads}/{cfg.n_kv_heads}) must divide tp {tp}")

    pspecs = jax.tree.map(lambda s: fit_spec(mesh, s),
                          llama_pp_param_specs(cfg),
                          is_leaf=lambda x: isinstance(x, P))
    tok_spec = fit_spec(mesh, P("dp", None))

    def local_loss(params, tokens):
        # tokens: dp-local [b, T] — the forward runs on ALL T (kernel
        # block alignment; same all-T contract as next_token_loss) and
        # the last position's logits are dropped from the loss
        b, t = tokens.shape
        if b % n_microbatches:
            raise ValueError(
                f"local batch {b} % microbatches {n_microbatches} != 0")
        mb = b // n_microbatches
        inp = tokens.reshape(n_microbatches, mb, t)
        tgt = jnp.pad(tokens[:, 1:], ((0, 0), (0, 1))
                      ).reshape(n_microbatches, mb, t)
        x = jnp.take(params["embed"], inp, axis=0)      # [M, mb, T, d]
        positions = jnp.broadcast_to(
            jnp.arange(t, dtype=jnp.int32), (mb, t))

        def stage(x_mb):
            def layer(x, lp):
                return _megatron_layer(x, lp, positions, cfg,
                                       tp_axis), None
            x_mb, _ = lax.scan(layer, x_mb, params["layers"])
            return x_mb

        outs = spmd_pipeline(stage, x, n_stages=pp, axis_name="pp",
                             remat=cfg.remat)
        h = _rmsnorm(outs, params["final_norm"], cfg.norm_eps)
        logits = (h @ params["lm_head"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        # the last position has no next token (its padded target is 0):
        # exclude it from the mean
        valid = jnp.arange(t) < t - 1
        loss = -(ll * valid).sum() / (valid.sum() * ll.shape[0]
                                      * ll.shape[1])
        # outputs (hence loss) are valid on the last pp rank only
        loss = lax.psum(
            jnp.where(lax.axis_index("pp") == pp - 1, loss, 0.0), "pp")
        if "dp" in mesh.axis_names:
            loss = lax.pmean(loss, "dp")
        return loss

    from kubegpu_tpu.parallel.sharding import compat_shard_map
    return compat_shard_map(
        local_loss, mesh, in_specs=(pspecs, tok_spec),
        out_specs=P(), check=False)


def make_pp_train_step(cfg: LlamaConfig, optimizer, mesh: Mesh,
                       n_microbatches: int = 2):
    """(params, opt_state, tokens) → (params, opt_state, loss) with the
    pipelined loss; same contract as
    :func:`kubegpu_tpu.models.llama.make_train_step`."""
    import optax

    loss_fn = make_pp_loss(cfg, mesh, n_microbatches)

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step

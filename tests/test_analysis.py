"""KTP-Audit (ISSUE 9): the static-analysis subsystem must CATCH the
bad patterns it exists for (negative fixtures per rule, a deliberately
bad executable for the jaxpr auditor), HONOR the two blessing channels
(TOML entries, inline pins), and hold the repo itself clean — the
tier-1 gate that makes every rule a standing invariant rather than a
one-shot cleanup.

The compile-signature census drives real engine workloads through
real compiles, so it is ``slow``-marked here; tier-1 still runs it via
the ``cb_compile_census`` bench row (tests/test_bench_smoke.py).
"""

import os
import pathlib
import subprocess
import sys
import textwrap

import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from kubegpu_tpu.analysis.blessed import Blessings, inline_allow
from kubegpu_tpu.analysis.lint import (
    RULES,
    FileLinter,
    lint_metric_names,
    lint_package,
)

PKG_ROOT = pathlib.Path(__file__).resolve().parent.parent / "kubegpu_tpu"
EMPTY = Blessings({})


def _lint(tmp_path, src, *, subdir="models", name="bad.py",
          blessings=EMPTY):
    """Write a snippet under a fake package root and lint it.  The
    subdir matters: KTP002 only arms inside the device-code layers."""
    d = tmp_path / "fakepkg" / subdir
    d.mkdir(parents=True, exist_ok=True)
    p = d / name
    p.write_text(textwrap.dedent(src))
    return FileLinter(p, tmp_path / "fakepkg", blessings).run()


def _codes(findings, blessed=False):
    return sorted({f.code for f in findings if f.blessed == blessed})


# ---------------------------------------------------------------------------
# negative fixtures: each rule must fire on its known-bad snippet
# ---------------------------------------------------------------------------

def test_ktp001_pop_zero(tmp_path):
    fs = _lint(tmp_path, """\
        def drain(q):
            while q:
                item = q.pop(0)
            q.pop()          # pop from the END is fine
            return item
        """)
    assert _codes(fs) == ["KTP001"]
    assert len(fs) == 1 and fs[0].line == 3
    assert "deque" in fs[0].message


def test_ktp002_host_sync_variants(tmp_path):
    src = """\
        import jax
        import jax.numpy as jnp
        import numpy as np

        def leak(x):
            a = np.asarray(x)            # fetch 1
            b = x.item()                 # fetch 2
            c = float(jnp.sum(x))        # fetch 3
            return a, b, c
        """
    fs = _lint(tmp_path, src, subdir="models")
    assert _codes(fs) == ["KTP002"] and len(fs) == 3
    # the same code in a host layer is by-design and must NOT fire
    assert _lint(tmp_path, src, subdir="scheduler") == []


def test_ktp003_wall_clock_in_traced_fn(tmp_path):
    fs = _lint(tmp_path, """\
        import time
        import jax

        @jax.jit
        def tick(x):
            t0 = time.perf_counter()     # frozen into the executable
            return x + t0
        """)
    assert _codes(fs) == ["KTP003"]
    assert "tick" in fs[0].message


def test_ktp003_scope_aware_name_matching(tmp_path):
    # `Engine.step` is host code; the scan body that happens to share
    # the name `step` is the traced one.  Only the body's RNG fires.
    fs = _lint(tmp_path, """\
        import random
        import jax
        from jax import lax

        class Engine:
            def step(self):
                return random.random()   # host code: allowed

        def run(xs):
            def step(carry, x):
                return carry + random.random(), x
            return lax.scan(step, 0.0, xs)
        """)
    assert _codes(fs) == ["KTP003"] and len(fs) == 1
    assert fs[0].line == 11


def test_ktp004_undocumented_metric_name(tmp_path):
    root = tmp_path / "fakepkg"
    root.mkdir()
    (root / "mod.py").write_text(textwrap.dedent("""\
        def report(metrics):
            metrics.inc("serve_decode_stall_ms")   # in the TABLE
            metrics.inc("totally_novel_counter")   # not in the TABLE
        """))
    fs = [f for f in lint_metric_names(root, EMPTY) if not f.blessed]
    assert len(fs) == 1 and fs[0].code == "KTP004"
    assert "totally_novel_counter" in fs[0].message


def test_ktp004_series_and_alert_names_join_the_census(tmp_path):
    # ISSUE 20 satellite: SeriesStore windowed queries and AlertRule
    # name/series literals are metric names too — an undocumented one
    # fails the census exactly like a bogus .inc() name, while
    # documented names (and their _p50/_p99 percentile tracks) pass
    root = tmp_path / "fakepkg"
    root.mkdir()
    (root / "mod.py").write_text(textwrap.dedent("""\
        from kubegpu_tpu.obs.alerts import AlertRule

        def watch(store):
            store.rate("serve_failover_total", 8)      # in the TABLE
            store.avg("serve_ttft_ms_p99", 8)          # hist track: ok
            store.max("bogus_series_name", 8)          # not in TABLE
            return AlertRule(name="alert_failover_burn",
                             series="serve_failover_total")

        def bad_rule():
            return AlertRule(name="alert_made_up",
                             series="another_bogus_series")
        """))
    fs = [f for f in lint_metric_names(root, EMPTY) if not f.blessed]
    msgs = [f.message for f in fs]
    assert len(fs) == 3, msgs
    assert any("bogus_series_name" in m for m in msgs)
    assert any("alert_made_up" in m for m in msgs)
    assert any("another_bogus_series" in m for m in msgs)


def test_ktp005_unbounded_growth(tmp_path):
    fs = _lint(tmp_path, """\
        class RequestBatcher:
            def __init__(self):
                self.log: list = []
                self.ring = []
                self.pruned = []

            def tick(self, ev):
                self.log.append(ev)          # grows forever
                self.ring.append(ev)
                if len(self.ring) > 8:
                    self.ring.clear()        # evicted: fine
                self.pruned.append(ev)
                _prune_window(self.pruned)   # eviction helper: fine
        """)
    assert _codes(fs) == ["KTP005"] and len(fs) == 1
    assert ".log" in fs[0].message


def test_ktp006_inconsistent_locking(tmp_path):
    fs = _lint(tmp_path, """\
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def safe_inc(self):
                with self._lock:
                    self.n += 1

            def racy_inc(self):
                self.n += 1              # bare write, same attr
        """)
    assert _codes(fs) == ["KTP006"] and len(fs) == 1
    assert ".n" in fs[0].message


def test_ktp006_locked_suffix_convention(tmp_path):
    # a ``*_locked`` method's contract is caller-holds-lock; its
    # writes must not be reported as racy
    fs = _lint(tmp_path, """\
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def inc(self):
                with self._lock:
                    self._inc_locked()

            def _inc_locked(self):
                self.n += 1
        """)
    assert fs == []


def test_ktp007_undonated_serving_executable(tmp_path):
    # inside an engine factory, wrapping a pool-threading body with a
    # jit-family call and NO donate= is the silent 2x-HBM regression;
    # both wrap spellings (call and decorator) must fire, and a wrap
    # that declares donation — even donate=() — must not
    fs = _lint(tmp_path, """\
        import functools
        import jax
        from kubegpu_tpu.parallel.sharding import donating_jit

        def _paged_engine_fns(cfg, donate=True):
            def _block_body(params, pool, tokens):
                return tokens, pool

            @functools.partial(jax.jit)       # decorator wrap, bad
            def prefill_chunk(params, pool, chunk):
                return pool

            decode_block = jax.jit(_block_body)          # bad
            verify_block = donating_jit(                 # fine
                _block_body, donate=("pool",))
            off_block = donating_jit(_block_body, donate=())  # fine
            return decode_block, prefill_chunk, verify_block

        def host_helper(pool):
            return jax.jit(lambda p: p)(pool)   # not a factory: exempt
        """)
    assert _codes(fs) == ["KTP007"] and len(fs) == 2
    assert "donat" in fs[0].message


# ---------------------------------------------------------------------------
# blessing channels: TOML entries and inline pins
# ---------------------------------------------------------------------------

def test_toml_blessing_suppresses_with_reason(tmp_path):
    b = Blessings({"bless": [{
        "rule": "KTP001", "file": "models/bad.py", "func": "drain",
        "reason": "startup-only queue, N < 10"}]})
    fs = _lint(tmp_path, """\
        def drain(q):
            return q.pop(0)
        """, blessings=b)
    assert len(fs) == 1 and fs[0].blessed
    assert fs[0].reason == "startup-only queue, N < 10"
    # blessed findings still surface in the report's blessed bucket —
    # the allowlist stays reviewable, it does not hide code


def test_inline_pin_is_rule_specific(tmp_path):
    # a pin covers its own line or the line below it; a pin naming a
    # DIFFERENT rule covers nothing
    fs = _lint(tmp_path, """\
        def drain(q):
            a = q.pop(0)   # ktp: allow(KTP001) bench setup, N=3
            c = len(q)
            b = q.pop(0)   # ktp: allow(KTP005) wrong rule pinned
            return a, b, c
        """)
    by_line = {f.line: f for f in fs}
    assert by_line[2].blessed and "N=3" in by_line[2].reason
    assert not by_line[4].blessed


def test_inline_allow_helper():
    lines = ["x = 1", "y.pop(0)  # ktp: allow(KTP001) reason here"]
    assert inline_allow(lines, 2, "KTP001") == "reason here"
    assert inline_allow(lines, 2, "KTP002") is None


# ---------------------------------------------------------------------------
# jaxpr auditor: the deliberately-bad executable
# ---------------------------------------------------------------------------

def _bad_executable():
    import jax
    import jax.numpy as jnp
    import numpy as np

    def host_sum(a):
        return np.asarray(a, dtype=np.float32).sum(keepdims=True)

    def bad(x):                          # x is bf16
        y = jax.pure_callback(
            host_sum, jax.ShapeDtypeStruct((1,), jnp.float32), x)
        return x.astype(jnp.float32) + y   # silent bf16→f32 upcast

    return bad


def test_jaxpr_audit_catches_callback_and_upcast():
    import jax.numpy as jnp

    from kubegpu_tpu.analysis.jaxpr_audit import audit_jaxpr
    findings, stats = audit_jaxpr(
        _bad_executable(), (jnp.zeros((4,), jnp.bfloat16),),
        "bad_fixture", EMPTY)
    assert _codes(findings) == ["JXA001", "JXA002"]
    assert stats["callbacks"] >= 1 and stats["upcasts"] >= 1
    jxa2 = next(f for f in findings if f.code == "JXA002")
    assert "bfloat16" in jxa2.message and "bad_fixture" in jxa2.message


def test_jaxpr_audit_honors_upcast_allowlist():
    import jax.numpy as jnp

    from kubegpu_tpu.analysis.jaxpr_audit import audit_jaxpr
    b = Blessings({"jaxpr": {
        "upcast": [{"func": "bad", "reason": "fixture accumulator"}],
        "callback": [{"func": "bad", "reason": "fixture host hook"}]}})
    findings, _ = audit_jaxpr(
        _bad_executable(), (jnp.zeros((4,), jnp.bfloat16),),
        "bad_fixture", b)
    assert _codes(findings, blessed=False) == []
    assert _codes(findings, blessed=True) == ["JXA001", "JXA002"]


def test_jaxpr_audit_clean_fn_is_clean():
    import jax.numpy as jnp

    from kubegpu_tpu.analysis.jaxpr_audit import audit_jaxpr

    def clean(x):
        return (x * 2).sum()

    # f32 input: jnp.sum over bf16 would (correctly) flag the f32
    # accumulator upcast the allowlist exists for
    findings, stats = audit_jaxpr(
        clean, (jnp.zeros((4,), jnp.float32),), "clean", EMPTY)
    assert findings == [] and stats["eqns"] >= 2


# ---------------------------------------------------------------------------
# the repo itself must pass — the standing tier-1 gate
# ---------------------------------------------------------------------------

def test_repo_clean_lints():
    bad = [f for f in lint_package(PKG_ROOT, Blessings.load())
           if not f.blessed]
    assert not bad, "\n".join(
        f"{f.path}:{f.line} {f.code} {f.message}" for f in bad)


def test_repo_clean_jaxpr_audit():
    from kubegpu_tpu.analysis.jaxpr_audit import audit_engine_executables
    findings, summary = audit_engine_executables(Blessings.load())
    bad = [f for f in findings if not f.blessed]
    assert not bad, "\n".join(
        f"{f.path}:{f.line} {f.code} {f.message}" for f in bad)
    # every serving executable was actually traced, on all engines
    assert summary["total_eqns"] > 1000
    labels = {k.split(":", 1)[0] for k in summary["executables"]}
    assert labels == {"bf16", "int8", "int4"}
    assert all(s["eqns"] > 0 for s in summary["executables"].values())


def test_cli_flags_nonzero_on_bad_fixture(tmp_path):
    root = tmp_path / "fixture"
    root.mkdir()
    (root / "hot.py").write_text("def f(q):\n    return q.pop(0)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "kubegpu_tpu.analysis",
         "--lint-only", "--root", str(root)],
        capture_output=True, text=True,
        cwd=PKG_ROOT.parent, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "KTP001" in proc.stdout
    assert "hot.py:2" in proc.stdout          # rule code + file:line


def test_rule_table_is_mirrored_in_docs():
    import kubegpu_tpu.analysis as an
    for code, summary in RULES.items():
        assert code in (an.__doc__ or ""), code


# ---------------------------------------------------------------------------
# compile-signature census (compiles for real → slow; tier-1 coverage
# comes from the cb_compile_census bench row)
# ---------------------------------------------------------------------------

def test_expected_signature_sets_are_wellformed():
    from kubegpu_tpu.analysis.jaxpr_audit import expected_signatures
    exp = expected_signatures()
    assert set(exp) == {"plain", "spec", "q4"}
    assert len(exp["plain"]) == 8 and len(exp["spec"]) == 6
    # the int4 engine must not introduce any new top-level shapes
    assert exp["q4"] == exp["plain"]
    for sig in exp["plain"] | exp["spec"]:
        name = sig.split("(", 1)[0]
        assert name in {"decode_block", "decode_fused", "prefill_wave",
                        "prefill_chunk", "adopt_wave", "activate_slot",
                        "verify_block", "verify_fused", "export_chain",
                        "import_chain"}, sig


@pytest.mark.slow
def test_compile_census_matches_expected_set():
    from kubegpu_tpu.analysis.jaxpr_audit import compile_census
    findings, summary = compile_census()
    assert findings == [], "\n".join(f.message for f in findings)
    assert summary["signatures_total"] == 22
    for label in ("plain", "spec", "q4"):
        eng = summary["engines"][label]
        assert eng["observed"] == eng["expected"]
        assert eng["total_first_compile_ms"] > 0

"""The device scheduler: extender verbs + gang queue + annotation truth.

Reference call stack parity (SURVEY.md §4.2):
  kube-scheduler → /filter → /prioritize → bind
  device-scheduler: fill AllocateFrom, TakePodResources, PATCH annotations
Here the same phases run in-process: ``run_once()`` plays the vanilla
scheduler picking pods off the queue; filter/prioritize/allocate are the
extender webhook verbs (exposed for API parity and used internally); the
allocation annotation write-back + bind complete the path.

Gang atomicity (SURVEY.md §8 hard part): the extender pattern sees one pod
at a time, so gang state lives here — pods of a gang are *held* (never
partially placed) until every member has arrived and a whole-gang
assignment exists; then all members are committed/bound in one step.
No partial placement ⇒ no gang-vs-gang deadlock; FIFO with skip ⇒ no
head-of-line blocking.

Queue policy (k8s scheduler semantics, TPU-gang flavored):
- **priority**: units order by (priority desc, arrival); a unit with
  higher priority than an in-grace incomplete gang bypasses its barrier;
- **preemption**: a gang that doesn't fit may evict committed gangs of
  strictly lower priority — planned on cloned slice states (greedy evict
  lowest-priority-first, then minimized so no needless victim), victims
  requeued whole (gang semantics: members must restart together);
- **backfill**: while an incomplete gang holds the barrier, a later unit
  may still schedule if a what-if trial shows the barrier gang's
  projected request STILL fits after the unit is placed (conservative
  backfill — the blocked gang never loses its spot).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from kubegpu_tpu.allocator import GangAllocator, GangRequest, SliceState
from kubegpu_tpu.allocator.gang import GangAssignment, PodAssignment
from kubegpu_tpu.kubemeta import (
    FakeApiServer,
    Pod,
    PodPhase,
    pod_allocation,
    pod_gang_spec,
    pod_mesh_axes,
    pod_migratable,
    pod_multislice,
)
from kubegpu_tpu.kubemeta.codec import (
    ALLOCATE_FROM_KEY,
    MIGRATION_DEBT_KEY,
    migration_debt_from_annotation,
    migration_debt_to_annotation,
    allocation_to_annotation,
    node_advertisement,
    pod_workload_kind,
)
from kubegpu_tpu.kubemeta.objects import GangSpec
from kubegpu_tpu.obs import MetricsRegistry, ScheduleTrace, get_logger
from kubegpu_tpu.obs.spans import TRACE_ANNOTATION
from kubegpu_tpu.tpuplugin.backend import NodeAdvertisement

log = get_logger("scheduler")


@dataclass
class ScheduleResult:
    scheduled: list[str] = field(default_factory=list)   # pod names bound
    held: list[str] = field(default_factory=list)        # gang-waiting pods
    unschedulable: list[str] = field(default_factory=list)


@dataclass
class _PendingGang:
    spec: GangSpec
    pods: dict[int, Pod] = field(default_factory=dict)   # index → pod

    def complete(self) -> bool:
        return len(self.pods) == self.spec.size

    @property
    def priority(self) -> int:
        return max((p.spec.priority for p in self.pods.values()), default=0)


class DeviceScheduler:
    def __init__(self, api: FakeApiServer,
                 allocator: GangAllocator | None = None,
                 metrics: MetricsRegistry | None = None,
                 trace: ScheduleTrace | None = None,
                 coordinator_port: int = 8476,
                 gang_grace_s: float = 30.0,
                 max_planning_victims: int = 16,
                 bind_retries: int = 3,
                 tracer=None):
        self.api = api
        self.allocator = allocator or GangAllocator()
        self.metrics = metrics or MetricsRegistry()
        # request tracing (ISSUE 6): when a Tracer is attached, each
        # gang decision roots a trace whose propagation token rides the
        # bind annotation into the crishim env (the TPU_VISIBLE_CHIPS
        # road); a default-constructed ScheduleTrace shares the tracer
        # so decision events join request traces by gang id
        self.tracer = tracer
        self.trace = trace or ScheduleTrace(tracer=tracer)
        self.coordinator_port = coordinator_port
        # How long an INCOMPLETE gang at the head of the queue blocks
        # later-arrived units (the arrival grace; cf. Volcano/coscheduling
        # gang timeouts).  Expires → work conservation resumes, so two
        # half-arrived gangs can never deadlock the queue.
        self.gang_grace_s = gang_grace_s
        # Latency budget for what-if planning: a preemption/migration
        # plan tries at most this many victim evictions (each costs a
        # find_assignment) before declaring the request unplaceable —
        # bounds the p99 tail of failing decisions (VERDICT r1 #3).
        self.max_planning_victims = max_planning_victims
        # Bounded retry budget for apiserver write CONFLICTS on the
        # bind path (a lost optimistic-concurrency race with another
        # writer bumping the pod's resourceVersion).  Today's behavior
        # without it: the race surfaces as a hard bind failure and the
        # whole decision is thrown away.  Retries back off with
        # jitter; exhaustion requeues (the extender verb returns an
        # error so kube-scheduler's retry loop re-runs the pod).
        self.bind_retries = bind_retries
        import random as _random
        self._bind_rng = _random.Random(0x5eed)
        self.slices: dict[str, SliceState] = {}
        self._committed: dict[str, GangAssignment] = {}  # gang → assignment
        self._pod_gang: dict[str, str] = {}              # pod name → gang
        self._gang_priority: dict[str, int] = {}         # committed gangs
        self._gang_migratable: dict[str, bool] = {}      # committed gangs
        self._gang_first_seen: dict[str, float] = {}     # incomplete gangs
        # migration debts: a migrated gang's re-ask stays protected (same
        # what-if machinery as the barrier) until it re-places, so no
        # other unit — same pass or later — can take its proven home
        self._migration_debts: dict[str, GangRequest] = {}
        # Wire-path (webhook) gang assumption state: gkey → per-pod
        # (node, Allocation) decisions computed when the LAST member
        # arrived at /filter; occupancy is committed ("assumed") at that
        # moment so later wire/in-process decisions see it.  In-memory
        # only — sync() drops unfulfilled assumptions (unbound chips are
        # absent from annotation truth, so they free automatically).
        self._wire_assumed: dict[str, dict[str, tuple[str, object]]] = {}
        self._wire_assumed_at: dict[str, float] = {}
        self._wire_bound: dict[str, set[str]] = {}
        # One lock for every public entry point: the webhook's threaded
        # HTTP handlers, an embedded control loop, and advertiser ticks
        # may call in concurrently (advisor r1 finding).  RLock because
        # entry points call each other (evict→return_pod_resources).
        self._lock = threading.RLock()
        self.sync()

    def warm_start(self) -> None:
        """Pay the one-time costs BEFORE the first real decision: load
        (building if stale) the native allocator core — its lazy
        ``make -s`` + dlopen was the bulk of the r3 wire bench's 506 ms
        first-decision outlier (p50 was 4.5 ms; VERDICT r3 weak #5) —
        and run throwaway placements per known slice topology so the
        ring-orientation geometry memos start hot.  Pure reads:
        ``find_assignment`` never commits."""
        from kubegpu_tpu.allocator import _native
        _native.get_lib()
        with self._lock:
            for st in self.slices.values():
                n = len(st.topo.chips)
                for pods, chips in ((1, 1), (min(n, 4), 1)):
                    self.allocator.find_assignment([st], GangRequest(
                        gang_name="__warm__", num_pods=pods,
                        chips_per_pod=chips,
                        mesh_axes={"dp": pods} if pods > 1 else None))

    def serving_metrics(self) -> dict:
        """Serving-workload gauges the node agents harvested into this
        scheduler's registry (``harvest_workload_metrics`` stores every
        pod-printed metric line as ``workload_<name>``), keyed without
        the prefix: engine config echo, throughput, decode-stall
        percentiles — and, with the speculative serving engine, the
        pod's draft ACCEPTANCE (``serve_engine_spec_accept_rate``) and
        fused-tick token yield.  Acceptance is mirrored into the
        ``serving_spec_acceptance`` gauge so the extender's scrape
        surface (GET /metrics) carries it as a first-class scheduler
        signal: a slice whose pods accept ~0 is paying draft compute
        for nothing, which is a placement/config smell the operator
        should see next to schedule latency, not buried in pod logs.

        Fault-tolerance gauges ride the same harvest (ISSUE 4): the
        serve pod echoes ``serve_failover_total`` / ``serve_requests_
        retried`` / ``serve_slots_quarantined``, mirrored here into
        ``serving_failover_total`` etc. — a slice whose serving pods
        fail over repeatedly is a health signal the scheduler should
        surface next to gang evictions, not bury in pod stdout."""
        with self._lock:
            snap = self.metrics.snapshot()["gauges"]
        out = {k[len("workload_"):]: v for k, v in snap.items()
               if k.startswith("workload_serve_")}
        acc = out.get("serve_engine_spec_accept_rate")
        if acc is not None:
            self.metrics.set_gauge("serving_spec_acceptance", acc)
        # HBM accounting rides the same harvest (ISSUE 10): live/peak
        # pool bytes per pod, mirrored so capacity planning reads the
        # engine's real donation-era footprint off the scrape surface
        # overload signals ride it too (ISSUE 13): goodput-under-SLO
        # and shed/preempt/deadline pressure per pod — the scheduler
        # finally consumes load, so placement can react to a slice
        # that is shedding its paying tiers rather than just to one
        # that is dying
        for src, dst in (
                ("serve_failover_total", "serving_failover_total"),
                ("serve_requests_retried", "serving_requests_retried"),
                ("serve_slots_quarantined",
                 "serving_slots_quarantined"),
                ("serve_hbm_pool_bytes", "serving_hbm_pool_bytes"),
                ("serve_hbm_peak_bytes", "serving_hbm_peak_bytes"),
                ("serve_goodput_tokens_per_s",
                 "serving_goodput_tokens_per_s"),
                ("serve_slo_attainment", "serving_slo_attainment"),
                ("serve_requests_shed", "serving_requests_shed"),
                ("serve_requests_preempted",
                 "serving_requests_preempted"),
                ("serve_deadline_miss", "serving_deadline_miss"),
                # the closed loop (ISSUE 14): routing affinity and
                # autoscale state become first-class scheduler signals
                ("serve_replicas_active", "serving_replicas_active"),
                ("serve_autoscale_events",
                 "serving_autoscale_events"),
                ("serve_routing_affinity_hits",
                 "serving_routing_affinity_hits"),
                # kv compression & eviction (ISSUE 15): the scheduler
                # sees each pod's kv format, eviction pressure, and
                # the measured quality cost of running compressed
                ("serve_kv_bits", "serving_kv_bits"),
                ("serve_pages_evicted_total",
                 "serving_pages_evicted_total"),
                ("serve_kv_quality_delta",
                 "serving_kv_quality_delta"),
                # chip-tick spend (ISSUE 20): the pod's attributed
                # cost currency, so placement can weigh goodput per
                # chip-tick, not just goodput
                ("serve_chip_ticks_total",
                 "serving_chip_ticks_total")):
            v = out.get(src)
            if v is not None:
                self.metrics.set_gauge(dst, v)
        return out

    def _write_retrying(self, fn, *args, **kw):
        """Run one apiserver write, retrying resourceVersion conflicts
        with jittered exponential backoff (``bind_retries`` attempts).
        The final attempt propagates — callers map the surviving
        Conflict to their own requeue semantics (the wire verb returns
        an error string; run_once lets the daemon's control-plane
        retry loop absorb it)."""
        from kubegpu_tpu.kubemeta.controlplane import Conflict
        delay = 0.002
        for _ in range(max(0, self.bind_retries)):
            try:
                return fn(*args, **kw)
            except Conflict:
                self.metrics.inc("bind_conflict_retries")
                time.sleep(delay * (0.5 + self._bind_rng.random()))
                delay = min(delay * 2, 0.05)
        return fn(*args, **kw)

    # ------------------------------------------------------------------
    # Identity: in-memory gang/pod keys are NAMESPACE-QUALIFIED so two
    # tenants may both run a gang called "train" (or a pod "worker-0")
    # without colliding in the scheduler's registries.  The wire format
    # (allocation annotations) keeps the bare gang name — namespace is
    # already carried by the Pod object itself.
    # ------------------------------------------------------------------

    @staticmethod
    def _gkey(namespace: str, name: str) -> str:
        return f"{namespace}/{name}"

    @staticmethod
    def _split_gkey(key: str) -> tuple[str, str]:
        ns, _, bare = key.partition("/")
        return ns, bare

    @staticmethod
    def _arrival(pod: Pod) -> int:
        """Queue position: the original arrival for requeued pods."""
        from kubegpu_tpu.kubemeta.codec import QUEUED_AT_KEY
        stamped = pod.metadata.annotations.get(QUEUED_AT_KEY)
        return int(stamped) if stamped else pod.metadata.resource_version

    # ------------------------------------------------------------------
    # Cluster-state cache (annotation truth)
    # ------------------------------------------------------------------

    def sync(self) -> None:
        """Rebuild slice states from Node advertisements and re-apply every
        live pod's allocation — the restart-recovery path (SURVEY.md §4.4:
        annotations, not memory, are the source of truth).  Unfulfilled
        wire-path gang assumptions are dropped: their unbound chips exist
        nowhere in annotation truth, so they free here, and the external
        scheduler's next /filter re-assumes from live state."""
        with self._lock:
            self._sync_locked()

    def _sync_locked(self) -> None:
        self._wire_assumed.clear()
        self._wire_assumed_at.clear()
        self._wire_bound.clear()
        advs: dict[str, list[NodeAdvertisement]] = {}
        for node in self.api.list("Node"):
            if not node.status.ready:
                continue
            adv = node_advertisement(node)
            if adv is not None:
                advs.setdefault(adv.slice_id, []).append(adv)
        self.slices = {
            sid: SliceState.from_advertisements(a) for sid, a in advs.items()
        }
        self._committed.clear()
        self._pod_gang.clear()
        self._gang_priority.clear()
        self._gang_migratable.clear()
        gang_pods: dict[str, list] = {}
        for pod in self.api.list("Pod"):
            if pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                continue
            alloc = pod_allocation(pod)
            if alloc is None:
                continue
            if alloc.slice_id in self.slices:
                self.slices[alloc.slice_id].take(alloc.chips)
            ns = pod.metadata.namespace
            gang = self._gkey(ns, alloc.gang_name or pod.name)
            self._pod_gang[self._gkey(ns, pod.name)] = gang
            self._gang_priority[gang] = max(
                self._gang_priority.get(gang, pod.spec.priority),
                pod.spec.priority)
            self._gang_migratable[gang] = (
                self._gang_migratable.get(gang, True)
                and pod_migratable(pod))
            gang_pods.setdefault(gang, []).append(alloc)
        # Rebuild committed assignments from annotation truth so later
        # completions release chips even across scheduler restarts/re-syncs.
        # Gangs whose slice vanished (all hosts down) are kept too — the
        # recovery controller must still see them to evict/requeue, else
        # they'd zombie as RUNNING pods bound to dead nodes.  Slice ids are
        # per-pod (a multislice gang spans several).
        # migration debts rebuild from annotation truth too: PENDING
        # requeued pods carry the serialized reservation, so a restart
        # between migration-eviction and re-placement keeps the mover's
        # proven home protected (advisor r1 finding)
        self._migration_debts.clear()
        for pod in self.api.list("Pod", phase=PodPhase.PENDING):
            payload = pod.metadata.annotations.get(MIGRATION_DEBT_KEY)
            if not payload:
                continue
            gs = pod_gang_spec(pod)
            gkey = self._gkey(pod.metadata.namespace,
                              gs.name if gs else pod.name)
            if gkey in self._migration_debts:
                continue   # every member carries the same debt
            req = migration_debt_from_annotation(gkey, payload)
            if req is not None:
                self._migration_debts[gkey] = req
        for gang, allocs in gang_pods.items():
            pods = []
            for a in sorted(allocs, key=lambda a: a.worker_id):
                st = self.slices.get(a.slice_id)
                pods.append(PodAssignment(
                    pod_index=a.worker_id,
                    node_name=a.node_name,
                    host_id=st.topo.chip_at(a.chips[0].coord).host_id
                    if st is not None and a.chips else 0,
                    chips=list(a.chips),
                    slice_id=a.slice_id))
            self._committed[gang] = GangAssignment(
                slice_id=allocs[0].slice_id, pods=pods,
                locality=0.0, score=0.0)
        self.trace.record("recover", detail={
            "slices": len(self.slices),
            "pods_with_allocations": len(self._pod_gang)})

    def observe_node_change(self) -> None:
        """Cheap re-sync on node add/remove/health events."""
        self.sync()

    # (sync/filter/prioritize/bind/run_once/return_pod_resources/
    # evict_gang all serialize on self._lock — the webhook's threaded
    # handlers and an embedded control loop may call in concurrently.)

    # ------------------------------------------------------------------
    # Extender verbs (webhook API parity — SURVEY.md §3 extender service)
    # ------------------------------------------------------------------

    def filter(self, pod: Pod, node_names: list[str]) -> tuple[list[str], dict[str, str]]:
        """Predicate: which candidate nodes could host this pod?
        Singles are judged against each node's *own* chips (a restricted
        slice view), matching the extender contract that /filter answers
        per-node.  GANG members go through hold-and-assume: until every
        member exists, all nodes fail with a "gang waiting" reason (the
        external scheduler's retry loop is the arrival barrier — the
        coscheduling-plugin pattern); once complete, one whole-gang
        assignment is computed and committed, and each member's /filter
        passes exactly its assigned node."""
        with self._lock:
            self._wire_expire()
            gspec = pod_gang_spec(pod)
            if gspec is not None:
                return self._filter_gang(pod, gspec, node_names)
            try:
                req = self._request_for_single(pod)
            except ValueError as e:
                return [], {n: f"invalid request: {e}" for n in node_names}
            quota_reason = self._quota_violation([pod], req)
            if quota_reason is not None:
                return [], {n: quota_reason for n in node_names}
            feasible: list[str] = []
            reasons: dict[str, str] = {}
            for name in node_names:
                st = self._slice_of_node(name)
                if req.total_chips == 0 and req.millitpu_per_pod == 0:
                    feasible.append(name)
                    continue
                if st is None:
                    reasons[name] = "node has no TPU advertisement"
                    continue
                asg = self.allocator.find_assignment(
                    [st.restricted_to_node(name)], req)
                if asg is not None:
                    feasible.append(name)
                else:
                    reasons[name] = \
                        "insufficient free contiguous chips on node"
            return feasible, reasons

    def _filter_gang(self, pod: Pod, gspec: GangSpec,
                     node_names: list[str]
                     ) -> tuple[list[str], dict[str, str]]:
        gkey = self._gkey(pod.metadata.namespace, gspec.name)
        if gkey not in self._wire_assumed:
            err = self._wire_assume(gkey, pod.metadata.namespace,
                                    gspec.name)
            if err is not None:
                return [], {n: err for n in node_names}
        entry = self._wire_assumed[gkey].get(pod.name)
        if entry is None:
            return [], {n: f"pod not a member of assumed gang "
                        f"{gspec.name}" for n in node_names}
        node, _ = entry
        if node in node_names:
            return [node], {n: f"gang {gspec.name} is assigned to {node}"
                            for n in node_names if n != node}
        return [], {n: f"gang {gspec.name} is assigned to {node}, not "
                    "offered as a candidate" for n in node_names}

    def prioritize(self, pod: Pod, node_names: list[str]) -> dict[str, float]:
        """0–10 score per node (extender /prioritize).  Singles are
        judged against the node's own chips; assumed gang members score
        10 on their assigned node and 0 elsewhere."""
        with self._lock:
            gspec = pod_gang_spec(pod)
            if gspec is not None:
                gkey = self._gkey(pod.metadata.namespace, gspec.name)
                entry = (self._wire_assumed.get(gkey) or {}).get(pod.name)
                node = entry[0] if entry else None
                return {n: 10.0 if n == node else 0.0 for n in node_names}
            try:
                req = self._request_for_single(pod)
            except ValueError:
                return {n: 0.0 for n in node_names}
            scores: dict[str, float] = {}
            for name in node_names:
                st = self._slice_of_node(name)
                if st is None or (req.total_chips == 0
                                  and req.millitpu_per_pod == 0):
                    scores[name] = 5.0 if st is None else 0.0
                    continue
                asg = self.allocator.find_assignment(
                    [st.restricted_to_node(name)], req)
                scores[name] = asg.score if asg is not None else 0.0
            return scores

    # ------------------------------------------------------------------
    # Wire-path bind (extender bindVerb) + gang assumption
    # ------------------------------------------------------------------

    def bind(self, pod_name: str, node_name: str,
             namespace: str = "default") -> str | None:
        """Extender ``bind`` verb — the allocation write-back the
        reference did at assume/bind time (SURVEY.md §4.2): fill
        AllocateFrom for the chosen node, PATCH it onto the pod as the
        allocation annotation, then bind.  Returns an error string (the
        ExtenderBindingResult.Error payload) or None on success.

        Singles allocate here, atomically under the lock, restricted to
        the chosen node.  Gang members consume the hold-and-assume
        decision made at /filter time (see :meth:`_wire_assume`); chips
        were committed then, so this only writes annotations + binding.

        Apiserver write CONFLICTS (a lost resourceVersion race) are
        retried ``bind_retries`` times with jittered backoff; if one
        survives anyway the verb returns an error — kube-scheduler's
        retry loop requeues the pod, and the next attempt re-reads
        fresh state.
        """
        from kubegpu_tpu.kubemeta.controlplane import Conflict
        with self._lock:
            try:
                return self._bind_locked(pod_name, node_name, namespace)
            except Conflict as e:
                self.metrics.inc("bind_conflict_requeued")
                return (f"bind conflict persisted after "
                        f"{self.bind_retries} retries; pod requeued "
                        f"for re-scheduling: {e}")

    def _bind_locked(self, pod_name: str, node_name: str,
                     namespace: str) -> str | None:
        if True:
            t0 = time.perf_counter()
            self._wire_expire()
            from kubegpu_tpu.kubemeta import NotFound

            try:
                pod = self.api.get("Pod", pod_name, namespace=namespace)
            except NotFound:
                return f"pod {namespace}/{pod_name} not found"
            alloc = pod_allocation(pod)
            if alloc is not None:
                # idempotent completion (retry after a half-applied bind)
                if alloc.node_name != node_name:
                    return (f"pod already allocated on {alloc.node_name}, "
                            f"refusing bind to {node_name}")
                self._write_retrying(self.api.bind_pod, pod_name,
                                     node_name, namespace=namespace)
                # a gang member retried here still counts toward its
                # assumption's completion — otherwise the assumption
                # never fulfills and expiry frees chips this pod OWNS
                # per its annotation (review r2 finding)
                gspec = pod_gang_spec(pod)
                if gspec is not None:
                    gkey = self._gkey(namespace, gspec.name)
                    if gkey in self._wire_assumed:
                        self._wire_note_bound(gkey, pod.name, t0)
                return None
            gspec = pod_gang_spec(pod)
            if gspec is not None:
                return self._bind_gang_member(pod, gspec, node_name, t0)
            return self._bind_single(pod, node_name, t0)

    def _bind_single(self, pod: Pod, node_name: str,
                     t0: float) -> str | None:
        ns = pod.metadata.namespace
        try:
            req = self._request_for_single(pod)
        except ValueError as e:
            return f"invalid request: {e}"
        quota_reason = self._quota_violation([pod], req)
        if quota_reason is not None:
            self.metrics.inc("schedule_quota_denied")
            return quota_reason
        gkey = self._gkey(ns, pod.name)
        if req.total_chips == 0 and req.millitpu_per_pod == 0:
            self._write_retrying(self.api.bind_pod, pod.name, node_name,
                                 namespace=ns)
            self._observe_latency(t0, gkey, scheduled=True)
            return None
        st = self._slice_of_node(node_name)
        if st is None:
            return f"node {node_name} has no TPU advertisement"
        asg = self.allocator.find_assignment(
            [st.restricted_to_node(node_name)], req)
        if asg is None:
            self._observe_latency(t0, gkey, scheduled=False)
            return (f"insufficient free contiguous chips on {node_name}")
        coordinator, hostnames = GangAllocator.coordinator_for(
            asg, self.slices, port=self.coordinator_port)
        allocations = asg.to_allocations(coordinator, hostnames)
        self.allocator.commit(self.slices, asg)
        self._committed[gkey] = asg
        self._gang_priority[gkey] = pod.spec.priority
        self._gang_migratable[gkey] = pod_migratable(pod)
        self._pod_gang[gkey] = gkey
        self._trace_schedule_root(gkey, t0, locality=asg.locality)
        self._write_retrying(
            self.api.patch_annotations, "Pod", pod.name,
            {ALLOCATE_FROM_KEY: allocation_to_annotation(allocations[0]),
             MIGRATION_DEBT_KEY: None,   # repaid via the wire path too
             **self._trace_bind_annotation(gkey, pod.name, node_name)},
            namespace=ns)
        self._write_retrying(self.api.bind_pod, pod.name, node_name,
                             namespace=ns)
        self.metrics.observe("allocation_locality", asg.locality)
        self._observe_latency(t0, gkey, scheduled=True)
        self.trace.record("bind", gang=gkey, detail={
            "node": node_name, "locality": asg.locality})
        return None

    def _bind_gang_member(self, pod: Pod, gspec: GangSpec,
                          node_name: str, t0: float) -> str | None:
        ns = pod.metadata.namespace
        gkey = self._gkey(ns, gspec.name)
        if gkey not in self._wire_assumed:
            err = self._wire_assume(gkey, ns, gspec.name)
            if err is not None:
                return err
        entry = self._wire_assumed[gkey].get(pod.name)
        if entry is None:
            return f"pod is not a member of assumed gang {gspec.name}"
        node, alloc = entry
        if node != node_name:
            return (f"gang member is assigned to {node}, refusing bind "
                    f"to {node_name}")
        self._trace_schedule_root(gkey, t0, wire=True)
        self._write_retrying(
            self.api.patch_annotations, "Pod", pod.name,
            {ALLOCATE_FROM_KEY: allocation_to_annotation(alloc),
             MIGRATION_DEBT_KEY: None,   # repaid via the wire path too
             **self._trace_bind_annotation(gkey, pod.name, node_name)},
            namespace=ns)
        self._write_retrying(self.api.bind_pod, pod.name, node_name,
                             namespace=ns)
        self._wire_note_bound(gkey, pod.name, t0)
        return None

    def _wire_note_bound(self, gkey: str, pod_name: str,
                         t0: float) -> None:
        """Record one member's successful bind; on the last one the
        assumption is fulfilled and forgotten (annotations are now the
        whole truth)."""
        bound = self._wire_bound.setdefault(gkey, set())
        bound.add(pod_name)
        if bound == set(self._wire_assumed.get(gkey, ())):
            asg = self._committed.get(gkey)
            self._wire_assumed.pop(gkey, None)
            self._wire_assumed_at.pop(gkey, None)
            self._wire_bound.pop(gkey, None)
            if asg is not None:
                self.metrics.observe("allocation_locality", asg.locality)
            self._observe_latency(t0, gkey, scheduled=True)
            self.trace.record("bind", gang=gkey, detail={
                "pods": len(bound), "complete": True})

    def _wire_assume(self, gkey: str, ns: str, bare: str) -> str | None:
        """Hold-and-assume for a gang arriving over the webhook: when
        every member exists PENDING in the apiserver, compute one
        whole-gang assignment against full cluster state, COMMIT its
        occupancy now (so concurrent decisions see it), and cache each
        member's (node, Allocation) for its /filter and /bind calls.
        Returns the failure reason (served as every node's FailedNodes
        entry — the external scheduler's retry loop is the arrival
        barrier), or None once assumed."""
        members: dict[int, Pod] = {}
        placed = 0
        size = 0
        for p in self.api.list("Pod", namespace=ns):
            gs = pod_gang_spec(p)
            if gs is None or gs.name != bare:
                continue
            if p.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                continue
            size = gs.size
            if pod_allocation(p) is not None:
                placed += 1
            elif p.status.phase == PodPhase.PENDING:
                members[gs.index] = p
        if placed and members:
            # Half-bound remnant of a LOST assumption (sync()/restart
            # between a gang's first and last bind): the pending members
            # can never re-assume (their siblings left PENDING), so the
            # gang would wedge forever.  Gang atomicity says members
            # restart together anyway — evict the whole gang; everyone
            # requeues PENDING and the external scheduler re-runs the
            # full flow from a clean slate (review r2 finding).
            self._evict_gang_locked(
                gang=gkey,
                reason="partially-bound gang from a lost wire "
                "assumption; requeued whole for re-scheduling")
            return (f"gang {bare}: partially-applied assumption was "
                    "lost; members requeued, retry scheduling")
        if placed and not members:
            return f"gang {bare}: already fully bound"
        if not members:
            return f"gang {bare}: no pending members visible"
        if len(members) < size or set(members) != set(range(size)):
            return f"gang {bare} waiting ({len(members)}/{size})"
        pods = [members[i] for i in range(size)]
        try:
            req = self._request_for_gang(gkey, pods)
        except ValueError as e:
            return f"invalid gang request: {e}"
        quota_reason = self._quota_violation(pods, req)
        if quota_reason is not None:
            self.metrics.inc("schedule_quota_denied")
            return quota_reason
        asg = self.allocator.find_assignment(
            list(self.slices.values()), req)
        if asg is None:
            return (f"gang {bare}: no contiguous placement for "
                    f"{req.total_chips} chips")
        coordinator, hostnames = GangAllocator.coordinator_for(
            asg, self.slices, port=self.coordinator_port)
        allocations = asg.to_allocations(coordinator, hostnames)
        self.allocator.commit(self.slices, asg)
        self._committed[gkey] = asg
        self._gang_priority[gkey] = max(p.spec.priority for p in pods)
        self._gang_migratable[gkey] = all(pod_migratable(p) for p in pods)
        entry: dict[str, tuple[str, object]] = {}
        for p, alloc in zip(pods, allocations):
            alloc.gang_name = bare
            self._pod_gang[self._gkey(ns, p.name)] = gkey
            entry[p.name] = (alloc.node_name, alloc)
        self._wire_assumed[gkey] = entry
        self._wire_assumed_at[gkey] = time.monotonic()
        self._wire_bound[gkey] = set()
        self.trace.record("wire-assume", gang=gkey, detail={
            "pods": size, "locality": asg.locality,
            "nodes": sorted({n for n, _ in entry.values()})})
        return None

    def _wire_expire(self) -> None:
        """Roll back assumptions the external scheduler abandoned (no
        bind within the gang grace): release the UNBOUND members' chips,
        shrink the committed assignment to the bound members (their
        allocations are annotation truth already), and forget the
        assumption so the next /filter re-assumes from live state."""
        now = time.monotonic()
        stale = [g for g, t in self._wire_assumed_at.items()
                 if now - t > self.gang_grace_s]
        for g in stale:
            entry = self._wire_assumed.pop(g)
            self._wire_assumed_at.pop(g, None)
            bound = self._wire_bound.pop(g, set())
            asg = self._committed.get(g)
            ns = self._split_gkey(g)[0]
            for name, (_, alloc) in entry.items():
                if name in bound:
                    continue
                st = self.slices.get(alloc.slice_id)
                if st is not None:
                    st.release(alloc.chips)
                self._pod_gang.pop(self._gkey(ns, name), None)
            if asg is None:
                continue
            if not bound:
                self._committed.pop(g, None)
                self._gang_priority.pop(g, None)
                self._gang_migratable.pop(g, None)
            else:
                bound_ids = {entry[n][1].worker_id for n in bound}
                self._committed[g] = GangAssignment(
                    slice_id=asg.slice_id,
                    pods=[p for p in asg.pods
                          if p.pod_index in bound_ids],
                    locality=asg.locality, score=asg.score)
            self.trace.record("wire-expire", gang=g, detail={
                "bound": len(bound), "assumed": len(entry)})

    # ------------------------------------------------------------------
    # Scheduling loop
    # ------------------------------------------------------------------

    def run_once(self) -> ScheduleResult:
        with self._lock:
            self._wire_expire()
            return self._run_once_locked()

    def _run_once_locked(self) -> ScheduleResult:
        """One pass over pending pods: group into gangs, place complete
        gangs atomically, write allocation annotations, bind.

        Units (singles and complete gangs) are scheduled in (priority
        desc, FIFO arrival) order — a gang's place in line is its FIRST
        member's arrival — so a late single can't grab the chip that
        blocks a gang which was queued ahead of it (fractional pods
        fragmenting a slice ahead of a whole-slice gang was the observed
        failure).  An INCOMPLETE gang at the head additionally blocks
        later units of its priority and below for ``gang_grace_s`` after
        its first member arrived — unless a what-if trial shows the unit
        can be *backfilled* without hurting ANY earlier-queued held
        unit's fit (in-grace gangs and backfill-denied units alike);
        when the grace expires, later units flow unconditionally again
        (deadlock-free work conservation)."""
        result = ScheduleResult()
        now = time.monotonic()
        pending = [p for p in self.api.list("Pod", phase=PodPhase.PENDING)
                   if p.spec.node_name is None]
        # FIFO by ORIGINAL arrival: an evicted+requeued pod carries its
        # first queue position (QUEUED_AT_KEY), so eviction never costs a
        # gang its seniority — without this, an equal-priority pending
        # unit could take the home a migration plan proved for a mover
        pending.sort(key=self._arrival)
        gangs: dict[str, _PendingGang] = {}
        units: list[tuple[str, object]] = []  # FIFO by first member
        for pod in pending:
            gspec = pod_gang_spec(pod)
            if gspec is None:
                units.append(("single", pod))
            else:
                gkey = self._gkey(pod.metadata.namespace, gspec.name)
                pg = gangs.get(gkey)
                if pg is None:
                    pg = gangs[gkey] = _PendingGang(spec=gspec)
                    units.append(("gang", gkey))
                pg.pods[gspec.index] = pod
        # forget incomplete-gang arrival times for gangs no longer pending
        self._gang_first_seen = {
            g: t for g, t in self._gang_first_seen.items() if g in gangs}
        # start every incomplete gang's grace clock at first-member
        # ARRIVAL, even while it waits behind a barrier — otherwise N
        # trickling gangs serve their graces serially (N·grace head-of-
        # line blocking instead of the documented per-gang bound)
        for gname, pg in gangs.items():
            if not pg.complete():
                self._gang_first_seen.setdefault(gname, now)

        def unit_priority(kind: str, unit) -> int:
            return (unit.spec.priority if kind == "single"
                    else gangs[unit].priority)

        def unit_key(kind: str, unit) -> str:
            return (self._gkey(unit.metadata.namespace, unit.name)
                    if kind == "single" else unit)

        # stable sort: priority desc, FIFO within equal priority
        units.sort(key=lambda ku: -unit_priority(*ku))

        # drop debts whose gang is gone entirely (user deleted the pods)
        present = {unit_key(k, u) for k, u in units}
        self._migration_debts = {
            g: r for g, r in self._migration_debts.items() if g in present}

        barrier: str | None = None  # incomplete gang blocking later units
        protected: list[GangRequest] = []  # held units' asks, queue order
        for kind, unit in units:
            if kind == "gang" and unit in self._wire_assumed:
                # mid-bind by an external scheduler over the webhook —
                # chips are already committed; don't double-place
                result.held.extend(
                    p.name for p in gangs[unit].pods.values())
                continue
            if kind == "gang" and not gangs[unit].complete():
                gname, pg = unit, gangs[unit]
                result.held.extend(p.name for p in pg.pods.values())
                first = self._gang_first_seen.get(gname, now)
                in_grace = now - first < self.gang_grace_s
                self.trace.record("hold", gang=gname, detail={
                    "have": len(pg.pods), "want": pg.spec.size,
                    "blocking": in_grace and barrier is None})
                if in_grace:
                    # in-grace gangs (head barrier AND later ones) keep
                    # their claim: later units must not steal their fit
                    if barrier is None:
                        barrier = gname
                    preq = self._projected_request(pg)
                    if preq is not None:
                        protected.append(preq)
                continue
            precomputed = None
            ukey = unit_key(kind, unit)
            # a debtor may take its own reserved home; everyone else must
            # prove the debts still fit after their placement
            debts = [r for g, r in self._migration_debts.items()
                     if g != ukey]
            if barrier is not None or debts:
                allowed, ureq, precomputed = self._may_backfill(
                    kind, unit, gangs, protected + debts)
                if not allowed:
                    names = ([unit.name] if kind == "single" else
                             [p.name for p in gangs[unit].pods.values()])
                    result.held.extend(names)
                    if ureq is not None:
                        # a held unit's ask is protected from LATER
                        # backfillers too — queue order is preserved
                        protected.append(ureq)
                    self.trace.record("defer", gang=unit if kind == "gang"
                                      else unit.name,
                                      detail={"behind": barrier
                                              or "migration-debt"})
                    continue
                if barrier is not None:
                    self.trace.record("backfill",
                                      gang=unit if kind == "gang"
                                      else unit.name,
                                      detail={"past": barrier})
            if kind == "single":
                pod = unit
                try:
                    req = self._request_for_single(pod)
                except ValueError as e:
                    self._reject(pod.name, [pod], str(e), result)
                    continue
                self._schedule_gang(
                    self._gkey(pod.metadata.namespace, pod.name),
                    [pod], req, result, priority=pod.spec.priority,
                    precomputed=precomputed)
                continue
            gkey = unit
            pg = gangs[gkey]
            self._gang_first_seen.pop(gkey, None)
            members = [pg.pods[i] for i in range(pg.spec.size)]
            try:
                req = self._request_for_gang(pg.spec.name, members)
            except ValueError as e:
                self._reject(gkey, members, str(e), result)
                continue
            self._schedule_gang(gkey, members, req, result,
                                priority=pg.priority,
                                precomputed=precomputed)
        return result

    # ------------------------------------------------------------------
    # Backfill (what-if trials on cloned slice states)
    # ------------------------------------------------------------------

    def _projected_request(self, pg: _PendingGang) -> GangRequest | None:
        """The request an incomplete gang WILL make once complete, shaped
        from its arrived members (gangs are homogeneous by contract)."""
        member = next(iter(pg.pods.values()))
        chips = member.spec.total_chips
        try:
            axes = self._sane_axes(pod_mesh_axes(member),
                                   pg.spec.size * chips)
            return GangRequest(
                gang_name=pg.spec.name,
                num_pods=pg.spec.size,
                chips_per_pod=chips,
                millitpu_per_pod=member.spec.total_millitpu,
                hbm_gib_per_chip=member.spec.max_hbm_gib,
                mesh_axes=axes,
                axis_weights=self._serving_weights(member, axes))
        except ValueError:
            return None

    def _may_backfill(self, kind: str, unit, gangs: dict,
                      protected: list[GangRequest]
                      ) -> tuple[bool, GangRequest | None,
                                 "GangAssignment | None"]:
        """Conservative backfill past the in-grace barrier: the unit may
        schedule iff a what-if trial shows every EARLIER-QUEUED held
        unit's request that fits today still fits after the unit is
        placed (requests are committed sequentially in queue order on
        both sides of the comparison).  Returns (allowed, request,
        assignment): the request comes back only when the unit is denied
        (so the caller can protect it from later backfillers); the probe
        assignment comes back on success so ``_schedule_gang`` doesn't
        repeat the placement search.  0-device units always pass (no TPU
        contention)."""
        try:
            if kind == "single":
                req = self._request_for_single(unit)
            else:
                pg = gangs[unit]
                req = self._request_for_gang(
                    unit, [pg.pods[i] for i in range(pg.spec.size)])
        except ValueError:
            return True, None, None  # rejected downstream; no resource risk
        if req.total_chips == 0 and req.millitpu_per_pod == 0:
            return True, None, None
        # find_assignment is read-only, so probe placement on the real
        # state first and clone only if the what-if comparison is needed
        asg = self.allocator.find_assignment(list(self.slices.values()), req)
        if asg is None:
            return False, req, None  # can't place now; held (not failed),
            #                          and protected against leapfrogging
        if not protected:
            return True, None, asg
        after = {sid: st.clone() for sid, st in self.slices.items()}
        self.allocator.commit(after, asg)
        before = {sid: st.clone() for sid, st in self.slices.items()}
        for preq in protected:
            a_before = self.allocator.find_assignment(
                list(before.values()), preq)
            if a_before is None:
                continue   # doesn't fit today anyway; can't be hurt
            self.allocator.commit(before, a_before)
            a_after = self.allocator.find_assignment(
                list(after.values()), preq)
            if a_after is None:
                return False, req, None
            self.allocator.commit(after, a_after)
        return True, None, asg

    def _reject(self, gang: str, members: list[Pod], reason: str,
                result: ScheduleResult) -> None:
        """Malformed requests must not abort the scheduling pass
        (one bad pod cannot starve the queue)."""
        result.unschedulable.extend(p.name for p in members)
        self.metrics.inc("schedule_invalid")
        self.trace.record("invalid", gang=gang, detail={"reason": reason})
        self._observe_latency(time.perf_counter(), gang, scheduled=False)

    def _effective_quota(self, ns: str):
        """Combined namespace budget — k8s ResourceQuota parity: EVERY
        quota object in the namespace enforces independently, so the
        effective limit per resource is the MINIMUM across the objects
        that specify it.  Returns a QuotaSpec, or None when the
        namespace has no quota objects (unlimited)."""
        from kubegpu_tpu.kubemeta import QuotaSpec

        quotas = self.api.list("Quota", namespace=ns)
        if not quotas:
            return None
        chips = [q.spec.tpu_chips for q in quotas
                 if q.spec.tpu_chips is not None]
        milli = [q.spec.millitpu for q in quotas
                 if q.spec.millitpu is not None]
        return QuotaSpec(tpu_chips=min(chips) if chips else None,
                         millitpu=min(milli) if milli else None)

    def _quota_violation(self, members: list[Pod],
                         req: GangRequest) -> str | None:
        """Namespace ResourceQuota check (k8s parity): would admitting
        this gang push the namespace's LIVE device usage past its
        combined quota?  Usage is computed from annotation truth, so it
        survives scheduler restarts like everything else.  Returns the
        human reason, or None when within budget."""
        ns = members[0].metadata.namespace
        quota = self._effective_quota(ns)
        if quota is None:
            return None   # no quota objects → unlimited
        ask_chips = req.total_chips
        ask_milli = req.num_pods * req.millitpu_per_pod
        used_chips, used_milli, _ = self._namespace_usage(ns)
        limit_c = quota.tpu_chips
        limit_m = quota.millitpu
        if limit_c is not None and used_chips + ask_chips > limit_c:
            return (f"namespace {ns} chip quota: {used_chips} used + "
                    f"{ask_chips} requested > {limit_c}")
        if limit_m is not None and used_milli + ask_milli > limit_m:
            return (f"namespace {ns} millitpu quota: {used_milli} used + "
                    f"{ask_milli} requested > {limit_m}")
        return None

    def _namespace_usage(self, ns: str) -> tuple[int, int, dict]:
        """(used_chips, used_millitpu, per-gang {gkey: (chips, milli)})
        over LIVE allocations in the namespace — annotation truth, shared
        by the quota gate and the quota-preemption planner.  Allocations
        only exist on bound/running pods, so the field selectors keep the
        apiserver from cloning the whole cluster."""
        used_c = used_m = 0
        per_gang: dict[str, tuple[int, int]] = {}
        for pod in self.api.list("Pod", namespace=ns,
                                 phase=(PodPhase.SCHEDULED,
                                        PodPhase.RUNNING)):
            alloc = pod_allocation(pod)
            if alloc is None:
                continue
            gkey = self._gkey(ns, alloc.gang_name or pod.name)
            c = sum(1 for ch in alloc.chips if ch.millichips >= 1000)
            m = sum(ch.millichips for ch in alloc.chips
                    if ch.millichips < 1000)
            used_c += c
            used_m += m
            gc, gm = per_gang.get(gkey, (0, 0))
            per_gang[gkey] = (gc + c, gm + m)
        return used_c, used_m, per_gang

    def _schedule_gang(self, gang_name: str, members: list[Pod],
                       req: GangRequest, result: ScheduleResult,
                       priority: int = 0,
                       precomputed: GangAssignment | None = None) -> None:
        """``gang_name`` is the namespace-qualified gang key."""
        t0 = time.perf_counter()
        # per-decision phase attribution (VERDICT r5 weak #5): the
        # expensive search phases are timed separately so the bench
        # can bucket what the slowest 1% of decisions spent their time
        # on — enumeration (incl. ordering), the multislice split
        # search, preemption planning, migration planning
        phases = {"enumerate": 0.0, "multislice_split": 0.0,
                  "preemption_plan": 0.0, "migration_plan": 0.0}

        def absorb():
            for k, v in getattr(self.allocator, "last_phase_ms",
                                {}).items():
                phases[k] = phases.get(k, 0.0) + v

        quota_reason = self._quota_violation(members, req)
        if quota_reason is not None \
                and any(p < priority for p in self._gang_priority.values()):
            # intra-tenant priority: evict the namespace's own
            # lower-priority gangs to free quota room (capacity preemption
            # alone never fires here — the quota gate precedes placement)
            victims = self._plan_quota_preemption(
                members[0].metadata.namespace, req, priority)
            if victims:
                for victim in victims:
                    self.metrics.inc("gangs_preempted")
                    self.evict_gang(
                        victim,
                        f"quota-preempted by {gang_name} (priority "
                        f"{priority} > "
                        f"{self._gang_priority.get(victim, 0)})")
                quota_reason = self._quota_violation(members, req)
        if quota_reason is not None:
            result.unschedulable.extend(p.name for p in members)
            self.metrics.inc("schedule_quota_denied")
            self.trace.record("quota", gang=gang_name,
                              detail={"reason": quota_reason})
            log.warning("quota_denied", gang=gang_name,
                        reason=quota_reason)
            self._observe_latency(t0, gang_name, scheduled=False)
            return
        # 0-device pods (CPU fallback, BASELINE config 1): bind to any
        # ready node, TPU-bearing or not.
        if req.total_chips == 0 and req.millitpu_per_pod == 0:
            nodes = [n for n in self.api.list("Node") if n.status.ready]
            if not nodes:
                result.unschedulable.extend(p.name for p in members)
                self._observe_latency(t0, gang_name, scheduled=False)
                return
            target = min(nodes, key=lambda n: n.name)
            for pod in members:
                self._write_retrying(self.api.bind_pod, pod.name,
                                     target.name,
                                     namespace=pod.metadata.namespace)
                result.scheduled.append(pod.name)
            self._observe_latency(t0, gang_name, scheduled=True)
            return

        # the backfill probe may have found the placement already (same
        # slice state — nothing mutates between probe and here)
        if precomputed is not None:
            asg = precomputed
        else:
            asg = self.allocator.find_assignment(
                list(self.slices.values()), req)
            absorb()
        preemptible = any(p < priority for p in self._gang_priority.values())
        if asg is None and preemptible:
            t_pre = time.perf_counter()
            victims = self._plan_preemption(req, priority)
            phases["preemption_plan"] += \
                (time.perf_counter() - t_pre) * 1e3
            if victims:
                for victim in victims:
                    self.metrics.inc("gangs_preempted")
                    self.evict_gang(
                        victim,
                        f"preempted by {gang_name} "
                        f"(priority {priority} > "
                        f"{self._gang_priority.get(victim, 0)})")
                asg = self.allocator.find_assignment(
                    list(self.slices.values()), req)
                absorb()
        if asg is None and any(self._gang_migratable.values()):
            # defragmentation: migrate MIGRATABLE gangs (checkpointed
            # workloads that tolerate a restart) to compact space — only
            # under a joint plan proving the requester fits AND every
            # migrated gang re-places afterwards
            t_mig = time.perf_counter()
            movers = self._plan_migration(req, priority)
            phases["migration_plan"] += \
                (time.perf_counter() - t_mig) * 1e3
            if movers:
                for victim in movers:
                    # record the mover's re-ask as a debt BEFORE evicting
                    # (the request needs the still-committed assignment)
                    vreq = self._request_for_committed(victim)
                    self.metrics.inc("gangs_migrated")
                    requeued = self.evict_gang(
                        victim,
                        f"migrated to defragment for {gang_name}")
                    if vreq is not None:
                        self._migration_debts[victim] = vreq
                        # persist on the requeued pods: a scheduler
                        # restart must not drop the home reservation
                        # (annotation truth — advisor r1 finding)
                        vns = self._split_gkey(victim)[0]
                        payload = migration_debt_to_annotation(vreq)
                        from kubegpu_tpu.kubemeta import NotFound
                        for pname in requeued:
                            try:
                                self.api.patch_annotations(
                                    "Pod", pname,
                                    {MIGRATION_DEBT_KEY: payload},
                                    namespace=vns)
                            except NotFound:
                                pass
                asg = self.allocator.find_assignment(
                    list(self.slices.values()), req)
                absorb()
        if asg is None:
            result.unschedulable.extend(p.name for p in members)
            self.metrics.inc("schedule_unschedulable")
            self.trace.record("fail", gang=gang_name, detail={
                "pods": len(members), "chips": req.total_chips,
                "millitpu": req.millitpu_per_pod,
                "total_ms": (time.perf_counter() - t0) * 1e3,
                "phase_ms": dict(phases)})
            # failed decisions are decisions: the MOST expensive paths
            # (full shape search + preemption + migration planning, all
            # failing) must land in the p50/p99 histogram, or the
            # headline number only measures the easy successes
            self._observe_latency(t0, gang_name, scheduled=False)
            return

        coordinator, hostnames = GangAllocator.coordinator_for(
            asg, self.slices, port=self.coordinator_port)
        allocations = asg.to_allocations(coordinator, hostnames)
        self.allocator.commit(self.slices, asg)
        self._committed[gang_name] = asg
        self._gang_priority[gang_name] = priority
        self._gang_migratable[gang_name] = all(
            pod_migratable(p) for p in members)
        self._migration_debts.pop(gang_name, None)   # debt repaid
        bare_gang = self._split_gkey(gang_name)[1]
        self._trace_schedule_root(gang_name, t0, slice=asg.slice_id,
                                  locality=asg.locality,
                                  score=asg.score)
        for pod, alloc in zip(members, allocations):
            alloc.gang_name = bare_gang   # wire format: bare name
            self._pod_gang[self._gkey(pod.metadata.namespace,
                                      pod.name)] = gang_name
            self.api.patch_annotations(
                "Pod", pod.name,
                {ALLOCATE_FROM_KEY: allocation_to_annotation(alloc),
                 # debt repaid: drop the persisted home reservation
                 MIGRATION_DEBT_KEY: None,
                 **self._trace_bind_annotation(
                     gang_name, pod.name, alloc.node_name)},
                namespace=pod.metadata.namespace)
            self._write_retrying(self.api.bind_pod, pod.name,
                                  alloc.node_name,
                                  namespace=pod.metadata.namespace)
            result.scheduled.append(pod.name)
        self.metrics.set_gauge("last_allocation_locality", asg.locality)
        self.metrics.observe("allocation_locality", asg.locality)
        self._observe_latency(t0, gang_name, scheduled=True)
        self.trace.record("schedule", gang=gang_name, detail={
            "slice": asg.slice_id, "locality": asg.locality,
            "score": asg.score,
            "nodes": sorted({p.node_name for p in asg.pods}),
            "total_ms": (time.perf_counter() - t0) * 1e3,
            "phase_ms": dict(phases)})
        log.info("schedule", gang=gang_name, slices=asg.slice_ids,
                 pods=len(members), locality=round(asg.locality, 4),
                 priority=priority)

    def _observe_latency(self, t0: float, gang: str, scheduled: bool) -> None:
        ms = (time.perf_counter() - t0) * 1e3
        self.metrics.observe("schedule_latency_ms", ms)
        self.metrics.inc("gangs_scheduled" if scheduled else "gangs_failed")

    # ------------------------------------------------------------------
    # Request tracing (ISSUE 6): root span per gang decision + a bind
    # span per pod whose context is THE propagation token
    # ------------------------------------------------------------------

    def _trace_schedule_root(self, gkey: str, t0: float, **attrs) -> None:
        """Root a trace for this gang's decision (backdated to t0) and
        link the gang id, so subsequent ScheduleTrace events and every
        downstream layer (crishim, engine) join the same trace.
        Idempotent: a gang already linked keeps its root."""
        if self.tracer is None or self.tracer.gang_context(gkey):
            return
        sp = self.tracer.start_span(
            "sched.schedule", attrs={"gang": gkey, **attrs})
        sp.t0 = t0
        self.tracer.link_gang(gkey, sp)
        sp.end()

    def _trace_bind_annotation(self, gkey: str, pod_name: str,
                               node: str) -> dict:
        """Record one pod's bind span and return the annotation
        fragment carrying its propagation token ({} when tracing is
        off — the patch stays byte-identical to the untraced build)."""
        if self.tracer is None:
            return {}
        with self.tracer.span("sched.bind",
                              parent=self.tracer.gang_context(gkey),
                              attrs={"gang": gkey, "pod": pod_name,
                                     "node": node}) as sp:
            token = sp.context.encode()
        return {TRACE_ANNOTATION: token}

    # ------------------------------------------------------------------
    # Pod lifecycle: return resources on completion/deletion (§4.4)
    # ------------------------------------------------------------------

    def return_pod_resources(self, pod_name: str, namespace: str) -> None:
        """Namespace is REQUIRED: pod identity is namespace-qualified,
        and a defaulted wrong namespace would silently no-op and leak the
        gang's chips until the next full sync."""
        with self._lock:
            gang = self._pod_gang.pop(self._gkey(namespace, pod_name),
                                      None)
            if gang is None:
                return
            # release only when the last member of the gang is gone
            if any(g == gang for g in self._pod_gang.values()):
                return
            self._gang_priority.pop(gang, None)
            self._gang_migratable.pop(gang, None)
            self._wire_assumed.pop(gang, None)
            self._wire_assumed_at.pop(gang, None)
            self._wire_bound.pop(gang, None)
            asg = self._committed.pop(gang, None)
            if asg is not None:
                # rollback skips vanished slices (multislice: free the rest)
                self.allocator.rollback(self.slices, asg)
                self.trace.record("release", gang=gang,
                                  detail={"slices": asg.slice_ids})

    # ------------------------------------------------------------------
    # Preemption + eviction (shared with the fault-recovery controller)
    # ------------------------------------------------------------------

    def _eviction_could_help(self, req: GangRequest) -> bool:
        """Exact necessary condition for ANY eviction plan to succeed:
        some slice (or, multislice, the union) must have enough chips
        that are healthy, advertised, and HBM-sufficient — occupancy
        aside, since eviction can only free occupancy.  O(chips); run
        before cloning slices and trial-evicting (p99 bound)."""
        if req.total_chips == 0:
            return True
        usable_total = 0
        for st in self.slices.values():
            if req.chips_per_pod > st.spec.chips_per_host:
                continue
            usable = sum(
                1 for c in st.available
                if c not in st.unhealthy
                and (req.hbm_gib_per_chip <= 0
                     or st.hbm_gib.get(c, 0.0) >= req.hbm_gib_per_chip))
            if usable >= req.total_chips:
                return True
            usable_total += usable
        return req.allow_multislice and usable_total >= req.total_chips

    def _greedy_evict_plan(self, order: list[str], req: GangRequest
                           ) -> tuple[list[str], dict] | None:
        """Shared planner skeleton (capacity preemption AND migration):
        on cloned slice states, roll victims back in ``order`` until
        ``req`` places, then a minimization pass re-admits any victim the
        fit doesn't actually need.  Returns (chosen victims, trial state
        with survivors committed and victims freed), or None when no set
        works (then nobody is evicted — no pointless thrash).

        Bounded: at most ``max_planning_victims`` evictions are tried
        (each costs a find_assignment); a plan needing more is treated
        as infeasible this pass, keeping the failing-decision latency
        tail flat under bin-packing pressure."""
        if not order or not self._eviction_could_help(req):
            return None
        order = order[:self.max_planning_victims]
        trial = {sid: st.clone() for sid, st in self.slices.items()}
        chosen: list[str] = []
        fits = False
        for victim in order:
            asg = self._committed[victim]
            if not any(sid in trial for sid in asg.slice_ids):
                continue   # every slice gone; eviction frees nothing
            self.allocator.rollback(trial, asg)
            chosen.append(victim)
            if self.allocator.find_assignment(
                    list(trial.values()), req) is not None:
                fits = True
                break
        if not fits:
            return None
        # minimize: re-admit victims the placement doesn't actually need
        for victim in list(chosen):
            asg = self._committed[victim]
            self.allocator.commit(trial, asg)
            if self.allocator.find_assignment(
                    list(trial.values()), req) is None:
                self.allocator.rollback(trial, asg)   # still required
            else:
                chosen.remove(victim)
        return chosen, trial

    def _plan_preemption(self, req: GangRequest,
                         priority: int) -> list[str] | None:
        """Victim gangs (strictly lower priority) whose eviction lets
        ``req`` fit.  Greedy lowest-priority first (newest commit breaks
        ties, k8s-style 'youngest victim'), minimized."""
        idx = {g: i for i, g in enumerate(self._committed)}
        order = sorted(
            (g for g in self._committed
             if self._gang_priority.get(g, 0) < priority),
            key=lambda g: (self._gang_priority.get(g, 0), -idx[g]))
        plan = self._greedy_evict_plan(order, req)
        return plan[0] if plan else None

    def _plan_quota_preemption(self, ns: str, req: GangRequest,
                               priority: int) -> list[str] | None:
        """Victims (strictly lower priority, SAME namespace — per-gang
        usage is namespace-scoped) whose eviction brings the namespace's
        usage plus ``req`` back under its Quota.  Greedy
        lowest-priority-first with newest-commit tie-break, then a
        minimization pass re-admits victims the budget doesn't need, then
        a PLACEMENT feasibility trial on cloned slice states (the evicted
        chips must actually let ``req`` place, counting a follow-up
        capacity preemption) — no eviction set is returned unless the
        whole plan succeeds, so quota pressure never thrash-kills gangs
        it cannot benefit from."""
        quota = self._effective_quota(ns)
        if quota is None:
            return None
        idx = {g: i for i, g in enumerate(self._committed)}
        order = sorted(
            (g for g in self._committed
             if self._gang_priority.get(g, 0) < priority),
            key=lambda g: (self._gang_priority.get(g, 0), -idx[g]))
        need_c = req.total_chips
        need_m = req.num_pods * req.millitpu_per_pod
        used_c, used_m, gang_usage = self._namespace_usage(ns)

        def fits(c: int, m: int) -> bool:
            if quota.tpu_chips is not None \
                    and c + need_c > quota.tpu_chips:
                return False
            if quota.millitpu is not None \
                    and m + need_m > quota.millitpu:
                return False
            return True

        chosen: list[str] = []
        for victim in order:
            if fits(used_c, used_m):
                break
            vc, vm = gang_usage.get(victim, (0, 0))
            if vc == 0 and vm == 0:
                continue   # other-namespace gang; frees no quota here
            used_c -= vc
            used_m -= vm
            chosen.append(victim)
        if not (fits(used_c, used_m) and chosen):
            return None
        # minimize: re-admit victims the budget doesn't actually need
        for victim in list(chosen):
            vc, vm = gang_usage.get(victim, (0, 0))
            if fits(used_c + vc, used_m + vm):
                used_c += vc
                used_m += vm
                chosen.remove(victim)
        # placement feasibility: with the victims' chips freed (plus any
        # follow-up capacity preemption of remaining lower-priority
        # gangs), must req actually place?  Otherwise evicting buys
        # nothing and the victims would thrash.
        if not self._eviction_could_help(req):
            return None
        trial = {sid: st.clone() for sid, st in self.slices.items()}
        for victim in chosen:
            asg = self._committed[victim]
            self.allocator.rollback(trial, asg)
        if self.allocator.find_assignment(
                list(trial.values()), req) is None:
            placed = False
            for victim in order[:self.max_planning_victims]:
                if victim in chosen:
                    continue
                asg = self._committed[victim]
                if not any(sid in trial for sid in asg.slice_ids):
                    continue
                self.allocator.rollback(trial, asg)
                if self.allocator.find_assignment(
                        list(trial.values()), req) is not None:
                    placed = True
                    break
            if not placed:
                return None
        return chosen

    def _request_for_committed(self, gang: str) -> GangRequest | None:
        """Rebuild a committed gang's request from its assignment +
        member annotations (the shape a migrated gang will re-ask for)."""
        asg = self._committed.get(gang)
        if asg is None or not asg.pods or not asg.pods[0].chips:
            return None
        chips_per_pod = len(asg.pods[0].chips)
        if asg.pods[0].chips[0].millichips < 1000:
            return None   # fractional singles aren't worth migrating
        members = self.gang_member_pods(gang)
        axes = pod_mesh_axes(members[0]) if members else None
        try:
            sane = self._sane_axes(axes, len(asg.pods) * chips_per_pod)
            return GangRequest(
                gang_name=gang, num_pods=len(asg.pods),
                chips_per_pod=chips_per_pod,
                # max across members — must match _request_for_gang's
                # floor or a migration plan could 'close' on chips the
                # real re-schedule then rejects (stranding the mover)
                hbm_gib_per_chip=max(
                    (p.spec.max_hbm_gib for p in members), default=0.0),
                mesh_axes=sane,
                axis_weights=(self._serving_weights(members[0], sane)
                              if members else None),
                allow_multislice=bool(members)
                and pod_multislice(members[0]))
        except ValueError:
            return None

    def _plan_migration(self, req: GangRequest,
                        priority: int) -> list[str] | None:
        """Defragmentation plan: the FEWEST MIGRATABLE committed gangs
        (priority <= requester — migration must never disturb more
        important work) whose eviction lets ``req`` place, under a JOINT
        feasibility trial: after placing ``req`` on the cloned state,
        every migrated gang's own request must re-place too (it was
        running; the plan must leave it a home, not strand it pending —
        and queue seniority preservation in evict_gang keeps pending
        units from stealing that home).  Returns None unless the whole
        plan closes."""
        idx = {g: i for i, g in enumerate(self._committed)}
        # largest-footprint first: each eviction frees the most space, so
        # the greedy loop disturbs the fewest gangs (minimization prunes
        # any leftovers); victims' re-ask requests are built LAZILY only
        # for the chosen few (each build lists the namespace's pods)
        order = sorted(
            (g for g in self._committed
             if self._gang_migratable.get(g, False)
             and self._gang_priority.get(g, 0) <= priority
             and self._committed[g].pods
             and self._committed[g].pods[0].chips
             and self._committed[g].pods[0].chips[0].millichips >= 1000),
            key=lambda g: (-sum(len(p.chips)
                                for p in self._committed[g].pods),
                           self._gang_priority.get(g, 0), -idx[g]))
        plan = self._greedy_evict_plan(order, req)
        if plan is None:
            return None
        chosen, trial = plan
        # joint closure: place req, then every mover must re-place
        req_asg = self.allocator.find_assignment(list(trial.values()), req)
        if req_asg is None:
            return None
        self.allocator.commit(trial, req_asg)
        for victim in chosen:
            vreq = self._request_for_committed(victim)
            if vreq is None:
                return None   # re-ask can't be rebuilt → no guarantee
            v_asg = self.allocator.find_assignment(
                list(trial.values()), vreq)
            if v_asg is None:
                return None   # would strand the migrated gang
            self.allocator.commit(trial, v_asg)
        return chosen

    def gang_member_pods(self, gang: str) -> list[Pod]:
        """LIVE members of a namespace-qualified gang key, identified by
        namespace + their allocation's gang name (annotation truth).
        Terminal pods are excluded: a completed member keeps its
        allocation annotation, and evicting it would silently resurrect
        and re-run a finished workload."""
        from kubegpu_tpu.kubemeta import pod_allocation

        ns, bare = self._split_gkey(gang)
        out = []
        for p in self.api.list("Pod", namespace=ns):
            if p.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                continue
            alloc = pod_allocation(p)
            if alloc is not None and (alloc.gang_name or p.name) == bare:
                out.append(p)
        return out

    def evict_gang(self, gang: str, reason: str,
                   requeue: bool = True) -> list[str]:
        """Whole-gang eviction + requeue (used by preemption here and by
        the fault-recovery controller): delete every live member (kills
        containers via node-agent reconcile, frees chips via the
        return-resources path), then recreate identical PENDING pods —
        same name/spec/gang, no binding, no allocation annotation — so the
        next pass schedules the gang fresh.  Returns requeued pod names.

        ``requeue=False`` is the SCALE-DOWN variant (ISSUE 14): the
        gang's capacity is being retired on purpose, so members are
        deleted but never recreated — the delete still flows to every
        watcher (the serving pool's health watch sees it), chips still
        free, and nothing re-enters the queue."""
        with self._lock:
            return self._evict_gang_locked(gang, reason, requeue)

    def _evict_gang_locked(self, gang: str, reason: str,
                           requeue: bool = True) -> list[str]:
        from kubegpu_tpu.kubemeta import NotFound
        from kubegpu_tpu.kubemeta.objects import ObjectMeta, PodStatus

        pods = self.gang_member_pods(gang)
        self.trace.record("evict", gang=gang, detail={
            "reason": reason, "pods": sorted(p.name for p in pods)})
        log.warning("evict", gang=gang, reason=reason, pods=len(pods))
        for pod in pods:
            try:
                self.api.delete("Pod", pod.name,
                                namespace=pod.metadata.namespace)
            except NotFound:
                pass
            # Belt-and-braces: free chips even when no lifecycle wiring
            # (e.g. scheduler used standalone in tests) — idempotent, the
            # first call pops the pod from the gang map.
            self.return_pod_resources(pod.name, pod.metadata.namespace)
        if not requeue:
            return [pod.name for pod in pods]
        from kubegpu_tpu.kubemeta.codec import QUEUED_AT_KEY

        requeued: list[str] = []
        for pod in pods:
            annotations = {k: v for k, v in pod.metadata.annotations.items()
                           if k != ALLOCATE_FROM_KEY}
            # preserve queue seniority across (repeated) evictions
            annotations.setdefault(QUEUED_AT_KEY,
                                   str(self._arrival(pod)))
            fresh = Pod(
                metadata=ObjectMeta(
                    name=pod.metadata.name,
                    namespace=pod.metadata.namespace,
                    labels=dict(pod.metadata.labels),
                    annotations=annotations),
                spec=pod.spec,
                status=PodStatus(phase=PodPhase.PENDING,
                                 message=f"requeued: {reason}"))
            fresh.spec.node_name = None
            self.api.create("Pod", fresh)
            requeued.append(fresh.name)
        return requeued

    def spawn_serving_gang(self, gang: str, size: int = 1,
                           chips: int = 1,
                           namespace: str = "default",
                           mesh_axes: dict[str, int] | None = None,
                           role: str | None = None) -> list[str]:
        """Scale-up half of the serving control loop (ISSUE 14):
        create ``size`` serving pods under gang ``gang`` and run one
        scheduling pass so they bind immediately — the SAME gang-
        scheduled path every hand-submitted serving pod takes (serving
        axis weights, role annotation and all), just driven by the
        autoscaler instead of an operator.  Returns the pod names;
        node agents start the containers on their next reconcile."""
        from kubegpu_tpu.cluster import tpu_pod   # lazy: no cycle
        from kubegpu_tpu.kubemeta import GangSpec
        from kubegpu_tpu.kubemeta.codec import set_pod_serve_role

        names: list[str] = []
        for k in range(size):
            pod = tpu_pod(
                f"{gang}-{k}", chips=chips, workload="serving",
                gang=GangSpec(name=gang, size=size, index=k),
                mesh_axes={"tp": chips} if mesh_axes is None
                else mesh_axes,
                namespace=namespace, command=["noop"])
            if role is not None:
                set_pod_serve_role(pod, role)
            self.api.create("Pod", pod)
            names.append(pod.metadata.name)
        self.run_once()
        return names

    # ------------------------------------------------------------------
    # Request construction
    # ------------------------------------------------------------------

    @staticmethod
    def _sane_axes(axes: dict[str, int] | None,
                   total_chips: int) -> dict[str, int] | None:
        """Drop a mesh-axes hint whose product doesn't match the chip ask
        (hints degrade gracefully; they never make a pod unschedulable)."""
        if not axes or total_chips <= 0:
            return None
        prod = 1
        for v in axes.values():
            prod *= v
        return axes if prod == total_chips else None

    @staticmethod
    def _serving_weights(pod: Pod, axes: dict[str, int] | None
                         ) -> dict[str, float] | None:
        """Serving gangs score their slices with the SERVING traffic
        model (topology sees serving slices as what they are): tp
        psums ride every decode step while dp replicas never exchange
        a byte — so the allocator should spend its contiguous ICI on
        the tp rings and may scatter replicas freely.  A disaggregated
        gang's role annotation (``serve-role``: prefill | decode)
        further relaxes tp tightness for prefill specialists, whose
        collectives hide behind batch compute."""
        if axes is None or pod_workload_kind(pod) != "serving":
            return None
        from kubegpu_tpu.kubemeta.codec import pod_serve_role
        from kubegpu_tpu.topology.locality import serving_axis_weights
        return serving_axis_weights(axes, role=pod_serve_role(pod))

    def _request_for_single(self, pod: Pod) -> GangRequest:
        chips = pod.spec.total_chips
        axes = self._sane_axes(pod_mesh_axes(pod), chips)
        return GangRequest(
            gang_name=pod.name,
            num_pods=1,
            chips_per_pod=chips,
            millitpu_per_pod=pod.spec.total_millitpu,
            hbm_gib_per_chip=pod.spec.max_hbm_gib,
            mesh_axes=axes,
            axis_weights=self._serving_weights(pod, axes),
        )

    def _request_for_gang(self, gang_name: str,
                          members: list[Pod]) -> GangRequest:
        per_pod = {p.spec.total_chips for p in members}
        milli = {p.spec.total_millitpu for p in members}
        if len(per_pod) != 1 or len(milli) != 1:
            raise ValueError(f"gang {gang_name}: heterogeneous asks")
        chips = per_pod.pop()
        axes = self._sane_axes(pod_mesh_axes(members[0]),
                               len(members) * chips)
        return GangRequest(
            gang_name=gang_name,
            num_pods=len(members),
            chips_per_pod=chips,
            millitpu_per_pod=milli.pop(),
            hbm_gib_per_chip=max(p.spec.max_hbm_gib for p in members),
            mesh_axes=axes,
            axis_weights=self._serving_weights(members[0], axes),
            allow_multislice=pod_multislice(members[0]),
        )

    def _slice_of_node(self, node_name: str) -> SliceState | None:
        for st in self.slices.values():
            if node_name in st.node_of_host.values():
                return st
        return None

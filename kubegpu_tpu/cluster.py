"""SimCluster: the in-process simulated control plane (SURVEY.md §5 (c)).

Wires fake apiserver + N node agents (one per TPU host VM, mock backend)
+ the device scheduler into one steppable cluster, so all five BASELINE
configs run end-to-end through the real scheduling/injection code — only
the transports (gRPC/HTTP) are collapsed into function calls.
"""

from __future__ import annotations

import itertools
import time

from kubegpu_tpu.crishim import FakeRuntime, NodeAgent, SubprocessRuntime
from kubegpu_tpu.kubemeta import (
    ContainerSpec,
    FakeApiServer,
    GangSpec,
    ObjectMeta,
    Pod,
    PodPhase,
    PodSpec,
    ResourceRequests,
    WatchEvent,
)
from kubegpu_tpu.kubemeta.codec import (
    set_pod_gang,
    set_pod_mesh_axes,
    set_pod_migratable,
    set_pod_multislice,
)
from kubegpu_tpu.obs import MetricsRegistry, ScheduleTrace
from kubegpu_tpu.scheduler import DeviceScheduler
from kubegpu_tpu.scheduler.health import FaultRecoveryController
from kubegpu_tpu.tpuplugin import mock_cluster

_port_counter = itertools.count(0)


def pick_coordinator_port() -> int:
    """Distinct ports per cluster so parallel tests' jax.distributed
    coordinators never collide."""
    return 8476 + (next(_port_counter) % 500)


def tpu_pod(name: str, chips: int = 0, millitpu: int = 0,
            gang: GangSpec | None = None,
            mesh_axes: dict[str, int] | None = None,
            command: list[str] | None = None,
            env: dict[str, str] | None = None,
            priority: int = 0,
            multislice: bool = False,
            namespace: str = "default",
            migratable: bool = False,
            hbm_gib: float = 0.0,
            workload: str | None = None) -> Pod:
    """Pod-spec builder — the user surface (reference: example/ YAML).
    ``workload="serving"`` annotates the traffic model: the scheduler
    scores the gang's slice with serving axis weights (tp hot on every
    decode step, dp-replica hops nearly free)."""
    pod = Pod(
        metadata=ObjectMeta(name=name, namespace=namespace),
        spec=PodSpec(containers=[ContainerSpec(
            name="main",
            command=command or [],
            env=env or {},
            resources=ResourceRequests(tpu_chips=chips, millitpu=millitpu,
                                       hbm_gib=hbm_gib),
        )], priority=priority),
    )
    if gang is not None:
        set_pod_gang(pod, gang)
    if mesh_axes is not None:
        set_pod_mesh_axes(pod, mesh_axes)
    if multislice:
        set_pod_multislice(pod)
    if migratable:
        set_pod_migratable(pod)
    if workload is not None:
        from kubegpu_tpu.kubemeta.codec import set_pod_workload_kind
        set_pod_workload_kind(pod, workload)
    return pod


class SimCluster:
    def __init__(self, slice_types: list[str], real_processes: bool = False,
                 extra_env: dict[str, str] | None = None,
                 config: "KubeTpuConfig | None" = None,
                 wire_cri: bool = False):
        from kubegpu_tpu.allocator import GangAllocator
        from kubegpu_tpu.config import KubeTpuConfig

        cfg = config or KubeTpuConfig()
        self.config = cfg
        self.api = FakeApiServer()
        self.metrics = MetricsRegistry()
        self.trace = ScheduleTrace(capacity=cfg.obs.trace_capacity)
        if real_processes or cfg.runtime.real_processes:
            merged_env = {**cfg.runtime.extra_env, **(extra_env or {})}
            self.runtime = SubprocessRuntime(extra_env=merged_env)
        else:
            self.runtime = FakeRuntime()
        self.cri_servers: list["CriServer"] = []
        self.agents = []
        for b in mock_cluster(slice_types):
            shim = None
            if wire_cri or cfg.runtime.wire_cri:
                # per-node CRI unix socket between agent (kubelet role)
                # and shim, as in the reference deployment (SURVEY §4.3)
                from kubegpu_tpu.crishim.criserver import (
                    CriServer,
                    RemoteCriShim,
                )
                server = CriServer(self.api, b, b.discover().node_name,
                                   self.runtime).start()
                self.cri_servers.append(server)
                shim = RemoteCriShim(server.socket_path)
            self.agents.append(NodeAgent(self.api, b, self.runtime,
                                         metrics=self.metrics, shim=shim))
        for a in self.agents:
            a.register()
        sc = cfg.scheduler
        self.scheduler = DeviceScheduler(
            self.api,
            allocator=GangAllocator(
                max_placements_per_shape=sc.max_placements_per_shape,
                locality_weight=sc.locality_weight,
                frag_weight=sc.frag_weight,
                fill_weight=sc.fill_weight),
            metrics=self.metrics, trace=self.trace,
            # explicit config port wins; 0 = auto, rotating per cluster so
            # parallel tests' jax.distributed coordinators never collide
            coordinator_port=sc.coordinator_port or pick_coordinator_port(),
            gang_grace_s=sc.gang_grace_s)
        self.recovery = FaultRecoveryController(
            self.api, self.scheduler, metrics=self.metrics, trace=self.trace)
        self._unsub = self.api.watch(self._on_event)

    @classmethod
    def from_config(cls, cfg: "KubeTpuConfig") -> "SimCluster":
        """Build a cluster entirely from the config tree (SURVEY.md §6
        config row: backend selection is a config field, mirroring the
        reference's plugin seam)."""
        if cfg.backend.type != "mock":
            raise NotImplementedError(
                "libtpu backend needs real hardware; SimCluster is the "
                "simulated control plane (use the mock backend)")
        if cfg.obs.json_logs:
            import logging

            from kubegpu_tpu.obs import configure_logging
            configure_logging(getattr(logging, cfg.obs.log_level.upper(),
                                      logging.INFO))
        return cls(list(cfg.backend.slice_types), config=cfg)

    # -- lifecycle events: free resources when pods finish/disappear -----

    def _on_event(self, ev: WatchEvent) -> None:
        if ev.kind != "Pod":
            return
        pod = ev.obj
        if ev.type == "DELETED" or (
                ev.type == "MODIFIED"
                and pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED)):
            self.scheduler.return_pod_resources(pod.name,
                                                pod.metadata.namespace)

    # -- driving ---------------------------------------------------------

    def submit(self, *pods: Pod) -> None:
        for p in pods:
            self.api.create("Pod", p)

    def set_quota(self, namespace: str, chips: int | None = None,
                  millitpu: int | None = None, name: str = "quota") -> None:
        """Create/replace a device quota object (k8s ResourceQuota
        parity — the scheduler denies asks that would exceed it).
        Several quota objects may coexist in one namespace; each enforces
        independently, so the tightest limit wins."""
        from kubegpu_tpu.kubemeta import NotFound, Quota, QuotaSpec

        try:
            self.api.delete("Quota", name, namespace=namespace)
        except NotFound:
            pass
        self.api.create("Quota", Quota(
            metadata=ObjectMeta(name=name, namespace=namespace),
            spec=QuotaSpec(tpu_chips=chips, millitpu=millitpu)))

    def step(self):
        """One control-plane tick: recover from faults, schedule pending,
        start bound pods."""
        self.recovery.run_once()
        result = self.scheduler.run_once()
        started = []
        for a in self.agents:
            started.extend(a.run_once())
        return result, started

    # -- fault injection (SURVEY.md §6: kill a host mid-gang, flap a
    #    link/chip — drives the elastic-recovery tests) ------------------

    def agent_for(self, node_name: str) -> NodeAgent:
        for a in self.agents:
            if a.node_name == node_name:
                return a
        raise KeyError(f"no agent for node {node_name}")

    def fail_host(self, node_name: str) -> None:
        """Machine death: containers die, node goes NotReady."""
        self.agent_for(node_name).fail()
        self.api.set_node_ready(node_name, False)

    def restore_host(self, node_name: str) -> None:
        self.agent_for(node_name).restore()
        self.api.set_node_ready(node_name, True)

    def fail_chip(self, node_name: str, local_index: int) -> None:
        a = self.agent_for(node_name)
        a.backend.fail_chip(local_index)
        a.advertise()

    def heal_chip(self, node_name: str, local_index: int) -> None:
        a = self.agent_for(node_name)
        a.backend.heal_chip(local_index)
        a.advertise()

    def fail_link(self, coord_a, coord_b, slice_id: str | None = None) -> None:
        """Flap an ICI link: every live agent owning an endpoint advertises
        the failure (both sides of a cross-host link report it).  Coords are
        slice-local, so with multiple slices of the same shape the link is
        ambiguous — ``slice_id`` is required then."""
        candidates = []
        for a in self.agents:
            if slice_id is not None and a.backend.slice_id != slice_id:
                continue
            topo = a.backend.topo
            if (topo.has_coord(tuple(coord_a))
                    and topo.has_coord(tuple(coord_b))):
                candidates.append(a)
        owning_slices = {a.backend.slice_id for a in candidates}
        if len(owning_slices) > 1:
            raise ValueError(
                f"link {coord_a}–{coord_b} exists in slices "
                f"{sorted(owning_slices)}; pass slice_id")
        owned = False
        for a in candidates:
            if not a.down and a.backend.fail_link(coord_a, coord_b):
                a.advertise()
                owned = True
        if not owned:
            raise ValueError(f"no live agent owns link {coord_a}–{coord_b}")

    def heal_link(self, coord_a, coord_b, slice_id: str | None = None) -> None:
        pair = (min(tuple(coord_a), tuple(coord_b)),
                max(tuple(coord_a), tuple(coord_b)))
        owners = [a for a in self.agents
                  if (slice_id is None or a.backend.slice_id == slice_id)
                  and pair in a.backend.bad_links]
        owning_slices = {a.backend.slice_id for a in owners}
        if len(owning_slices) > 1:  # symmetric with fail_link's ambiguity rule
            raise ValueError(
                f"link {coord_a}–{coord_b} is bad in slices "
                f"{sorted(owning_slices)}; pass slice_id")
        if not owners:
            raise ValueError(
                f"link {coord_a}–{coord_b} was not marked bad on any agent")
        for a in owners:
            a.backend.heal_link(coord_a, coord_b)
            if not a.down:
                a.advertise()

    def reap(self, timeout: float | None = None) -> dict[str, int]:
        codes: dict[str, int] = {}
        for a in self.agents:
            codes.update(a.reap(timeout=timeout))
        return codes

    def run_to_completion(self, timeout_s: float = 120.0,
                          tick_s: float = 0.02) -> dict[str, int]:
        """Step until every pod is terminal (or unschedulable pods remain
        and nothing is running).  Returns pod → exit code."""
        deadline = time.monotonic() + timeout_s
        exit_codes: dict[str, int] = {}
        while time.monotonic() < deadline:
            self.step()
            exit_codes.update(self.reap(timeout=tick_s))
            pods = self.api.list("Pod")
            if all(p.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED)
                   for p in pods):
                return exit_codes
            running = any(p.status.phase == PodPhase.RUNNING for p in pods)
            pending = [p for p in pods if p.status.phase == PodPhase.PENDING]
            if pending and not running:
                # give held gangs a chance; bail only if truly stuck
                r, _ = self.step()
                if not r.scheduled and not running and not r.held:
                    break
            time.sleep(0 if running else tick_s)
        return exit_codes

    def pod_phase(self, name: str) -> PodPhase:
        return self.api.get("Pod", name).status.phase

    def close(self) -> None:
        self._unsub()
        self.recovery.close()
        for a in self.agents:
            for h in a.handles.values():
                h.kill()
            if hasattr(a.shim, "close"):
                a.shim.close()
        for s in self.cri_servers:
            s.close()

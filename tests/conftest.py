"""Test harness config: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/pjit tests run
against ``xla_force_host_platform_device_count=8`` virtual CPU devices (the
same mechanism the driver's dryrun uses).

Environment subtlety (discovered the hard way): this image preloads jax at
interpreter start via a sitecustomize on PYTHONPATH that registers the
``axon`` TPU-tunnel platform, so setting ``JAX_PLATFORMS`` env vars here is
too late — ``jax.config.update`` after import is the reliable switch, and
XLA_FLAGS still works as long as no backend has initialized yet.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
# NB: subprocess workloads do NOT inherit env from here —
# SubprocessRuntime whitelists a minimal env; tests that launch real
# processes pass JAX_PLATFORMS via extra_env / the crishim's own injection.

import jax  # noqa: E402  (possibly already preloaded by sitecustomize)

jax.config.update("jax_platforms", "cpu")

"""HTTP apiserver façade + client — the last transport seam.

The reference's key architectural property (SURVEY.md §2): scheduler and
node agent never talk directly — ALL coordination flows through the
apiserver over HTTPS (client-go).  In-process, that role is played by
:class:`FakeApiServer`; this module puts the same surface on a real HTTP
wire so the node agent (crishim daemon, `crishim/serve.py`) can run as a
separate process, exactly as kubelet/crishim did:

- :class:`ApiServerHTTP` — REST façade over a FakeApiServer:
    POST   /apis/{kind}                     create
    GET    /apis/{kind}?namespace=&nodeName=&phase=&labelSelector=   list
    GET    /apis/{kind}/{ns}/{name}         get
    PUT    /apis/{kind}/{ns}/{name}         update (optimistic rv)
    PATCH  /apis/{kind}/{ns}/{name}         annotation strategic-merge
    DELETE /apis/{kind}/{ns}/{name}         delete
    POST   /apis/Pod/{ns}/{name}/binding    bind to node
    POST   /apis/Pod/{ns}/{name}/status     set phase (incarnation-safe)
    POST   /apis/Node/{ns}/{name}/ready     node readiness
    GET    /watch?since=SEQ&timeout=S       long-poll watch events

- :class:`HttpApiClient` — same METHOD surface as FakeApiServer (get /
  create / list / update / patch_annotations / bind_pod / set_pod_phase /
  set_node_ready / delete / watch), so NodeAgent, CriServer, and the
  scheduler run unmodified against either; NotFound/Conflict round-trip
  as status codes 404/409.

Watch semantics: the façade numbers every event with a monotonically
increasing sequence and keeps a bounded replay buffer; clients long-poll
``/watch?since=`` and are told to reset if they lag past the buffer
(k8s "too old resource version" semantics).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from kubegpu_tpu.kubemeta.controlplane import (
    Conflict,
    FakeApiServer,
    NotFound,
    WatchEvent,
)
from kubegpu_tpu.kubemeta.objects import PodPhase
from kubegpu_tpu.kubemeta.serialize import from_doc, to_doc
from kubegpu_tpu.obs import get_logger

log = get_logger("apiserver")

WATCH_BUFFER = 4096


class ApiServerHTTP:
    """REST façade over a FakeApiServer.  start() serves in a daemon
    thread; close() shuts down and unsubscribes the event tap."""

    def __init__(self, api: FakeApiServer, host: str = "127.0.0.1",
                 port: int = 0, metrics=None):
        self.api = api
        # /metrics scrape target (ISSUE 6): Prometheus text exposition
        # from the passed registry, defaulting to the process-global
        # one — the same convention the scheduler webhook serves
        self.metrics = metrics
        self._events: deque[tuple[int, WatchEvent]] = deque(
            maxlen=WATCH_BUFFER)
        self._seq = 0
        self._cond = threading.Condition()
        self._unsub = api.watch(self._on_event)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            # -- plumbing ---------------------------------------------

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def _send(self, code: int, doc: dict) -> None:
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _dispatch(self, method: str) -> None:
                try:
                    out = outer._route(method, self.path, self._body()
                                       if method in ("POST", "PUT", "PATCH")
                                       else {})
                    self._send(200, out)
                except NotFound as e:
                    self._send(404, {"error": str(e)})
                except Conflict as e:
                    self._send(409, {"error": str(e)})
                except (ValueError, KeyError) as e:
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})
                except Exception as e:   # pragma: no cover - last resort
                    log.error("apiserver_internal", path=self.path,
                              error=str(e))
                    self._send(500, {"error": str(e)})

            def do_GET(self):
                if self.path.partition("?")[0] == "/metrics":
                    # text exposition, not the JSON dispatch path
                    body = outer._metrics_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_PATCH(self):
                self._dispatch("PATCH")

            def do_DELETE(self):
                self._dispatch("DELETE")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: threading.Thread | None = None

    def _metrics_text(self) -> str:
        if self.metrics is not None:
            return self.metrics.to_prometheus()
        from kubegpu_tpu.obs.metrics import global_registry
        return global_registry.to_prometheus()

    # -- event tap ------------------------------------------------------

    def _on_event(self, ev: WatchEvent) -> None:
        with self._cond:
            self._seq += 1
            self._events.append((self._seq, ev))
            self._cond.notify_all()

    # -- lifecycle ------------------------------------------------------

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ApiServerHTTP":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        log.info("listening", address=self.address)
        return self

    def close(self) -> None:
        self._unsub()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- routing --------------------------------------------------------

    def _route(self, method: str, path: str, body: dict) -> dict:
        url = urllib.parse.urlparse(path)
        q = urllib.parse.parse_qs(url.query)
        parts = [p for p in url.path.split("/") if p]

        if parts == ["watch"] and method == "GET":
            if "tail" in q:   # new watcher: start from "now", no replay
                with self._cond:
                    return {"next": self._seq, "events": []}
            return self._watch_poll(
                since=int(q.get("since", ["0"])[0]),
                timeout=float(q.get("timeout", ["10"])[0]))

        if not parts or parts[0] != "apis":
            raise NotFound(f"no route {url.path}")
        if len(parts) == 2:  # /apis/{kind}
            kind = parts[1]
            if method == "POST":
                return to_doc(kind, self.api.create(
                    kind, from_doc(kind, body)))
            if method == "GET":
                return self._list(kind, q)
            raise ValueError(f"method {method} not allowed on collection")
        if len(parts) == 4:  # /apis/{kind}/{ns}/{name}
            kind, ns, name = parts[1], parts[2], parts[3]
            if method == "GET":
                return to_doc(kind, self.api.get(kind, name, namespace=ns))
            if method == "PUT":
                obj = from_doc(kind, body)
                return to_doc(kind, self.api.update(kind, obj))
            if method == "PATCH":
                return to_doc(kind, self.api.patch_annotations(
                    kind, name, body.get("annotations") or {},
                    namespace=ns))
            if method == "DELETE":
                self.api.delete(kind, name, namespace=ns)
                return {}
            raise ValueError(f"method {method} not allowed on object")
        if len(parts) == 5 and method == "POST":  # subresources
            kind, ns, name, sub = parts[1], parts[2], parts[3], parts[4]
            if kind == "Pod" and sub == "binding":
                self.api.bind_pod(name, body["node"], namespace=ns)
                return {}
            if kind == "Pod" and sub == "status":
                self.api.set_pod_phase(
                    name, PodPhase(body["phase"]),
                    message=body.get("message", ""),
                    exit_code=body.get("exitCode"),
                    namespace=ns,
                    expect_uid=body.get("expectUid"))
                return {}
            if kind == "Node" and sub == "ready":
                self.api.set_node_ready(name, bool(body["ready"]),
                                        namespace=ns)
                return {}
        raise NotFound(f"no route {method} {url.path}")

    def _list(self, kind: str, q: dict) -> dict:
        phase = None
        if "phase" in q:
            phase = tuple(PodPhase(v) for v in q["phase"][0].split(","))
        label_selector = None
        if "labelSelector" in q:
            label_selector = dict(
                kv.split("=", 1) for kv in q["labelSelector"][0].split(","))
        items = self.api.list(
            kind,
            label_selector,
            node_name=q.get("nodeName", [None])[0],
            phase=phase,
            namespace=q.get("namespace", [None])[0])
        return {"items": [to_doc(kind, o) for o in items]}

    def _watch_poll(self, since: int, timeout: float) -> dict:
        deadline = time.monotonic() + min(timeout, 60.0)
        with self._cond:
            while True:
                if self._events and self._events[0][0] > since + 1:
                    # events between `since` and the oldest buffered one
                    # were evicted: the client lags past the replay
                    # buffer — tell it to relist and skip ahead (k8s
                    # "resourceVersion too old" semantics)
                    return {"reset": True, "next": self._seq,
                            "events": []}
                fresh = [(s, ev) for s, ev in self._events if s > since]
                if fresh:
                    return {
                        "next": fresh[-1][0],
                        "events": [
                            {"seq": s, "kind": ev.kind, "type": ev.type,
                             "object": to_doc(ev.kind, ev.obj)}
                            for s, ev in fresh
                        ],
                    }
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"next": self._seq, "events": []}
                self._cond.wait(remaining)


# -- client -------------------------------------------------------------

class HttpApiClient:
    """FakeApiServer-compatible surface over the REST façade, so every
    component (NodeAgent, CriServer, scheduler) runs unmodified against
    a remote apiserver."""

    def __init__(self, base_url: str, timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._watchers: list[Callable[[WatchEvent], None]] = []
        self._reset_hooks: list[Callable[[], None]] = []
        self._watch_lock = threading.Lock()
        self._watch_thread: threading.Thread | None = None
        self._watch_stop = threading.Event()

    # -- transport ------------------------------------------------------

    def _call(self, method: str, path: str, body: dict | None = None,
              timeout: float | None = None) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout or self.timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            payload = {}
            try:
                payload = json.loads(e.read() or b"{}")
            except ValueError:
                pass
            msg = payload.get("error", str(e))
            if e.code == 404:
                raise NotFound(msg) from None
            if e.code == 409:
                raise Conflict(msg) from None
            raise ValueError(msg) from None

    # -- CRUD (FakeApiServer surface) -----------------------------------

    def create(self, kind: str, obj):
        return from_doc(kind, self._call(
            "POST", f"/apis/{kind}", to_doc(kind, obj)))

    def get(self, kind: str, name: str, namespace: str = "default"):
        return from_doc(kind, self._call(
            "GET", f"/apis/{kind}/{namespace}/{name}"))

    def list(self, kind: str, label_selector: dict[str, str] | None = None,
             *, node_name: str | None = None, phase=None,
             namespace: str | None = None):
        q = {}
        if label_selector:
            q["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in label_selector.items())
        if node_name is not None:
            q["nodeName"] = node_name
        if phase is not None:
            phases = phase if isinstance(phase, tuple) else (phase,)
            q["phase"] = ",".join(p.value for p in phases)
        if namespace is not None:
            q["namespace"] = namespace
        qs = ("?" + urllib.parse.urlencode(q)) if q else ""
        out = self._call("GET", f"/apis/{kind}{qs}")
        return [from_doc(kind, d) for d in out["items"]]

    def update(self, kind: str, obj):
        ns, name = obj.metadata.namespace, obj.metadata.name
        return from_doc(kind, self._call(
            "PUT", f"/apis/{kind}/{ns}/{name}", to_doc(kind, obj)))

    def patch_annotations(self, kind: str, name: str,
                          annotations: dict[str, str | None],
                          namespace: str = "default"):
        return from_doc(kind, self._call(
            "PATCH", f"/apis/{kind}/{namespace}/{name}",
            {"annotations": annotations}))

    def bind_pod(self, name: str, node_name: str,
                 namespace: str = "default") -> None:
        self._call("POST", f"/apis/Pod/{namespace}/{name}/binding",
                   {"node": node_name})

    def set_pod_phase(self, name: str, phase, message: str = "",
                      exit_code: int | None = None,
                      namespace: str = "default",
                      expect_uid: str | None = None) -> None:
        self._call("POST", f"/apis/Pod/{namespace}/{name}/status",
                   {"phase": getattr(phase, "value", str(phase)),
                    "message": message, "exitCode": exit_code,
                    "expectUid": expect_uid})

    def set_node_ready(self, name: str, ready: bool,
                       namespace: str = "default") -> None:
        self._call("POST", f"/apis/Node/{namespace}/{name}/ready",
                   {"ready": ready})

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        self._call("DELETE", f"/apis/{kind}/{namespace}/{name}")

    # -- watch ----------------------------------------------------------

    def watch(self, callback: Callable[[WatchEvent], None],
              on_reset: Callable[[], None] | None = None
              ) -> Callable[[], None]:
        """Subscribe via a shared background long-poll thread.  Events
        are re-materialized WatchEvents (objects deserialized), delivered
        in order.  Unsubscribe stops the thread when no watchers remain.

        ``on_reset`` fires when the server reports our position evicted
        from the replay buffer (events were LOST): cache-maintaining
        subscribers must relist, not merely continue."""
        with self._watch_lock:
            self._watchers.append(callback)
            if on_reset is not None:
                self._reset_hooks.append(on_reset)
            # (re)spawn when no thread runs OR the current one is
            # already winding down after a last-unsubscribe/stop: each
            # generation gets its OWN stop event, so a poller that is
            # still draining its final long-poll can't starve a fresh
            # subscriber of events
            if self._watch_thread is None or self._watch_stop.is_set():
                self._watch_stop = threading.Event()
                self._watch_thread = threading.Thread(
                    target=self._watch_loop, args=(self._watch_stop,),
                    daemon=True)
                self._watch_thread.start()

        def unsubscribe() -> None:
            with self._watch_lock:
                if callback in self._watchers:
                    self._watchers.remove(callback)
                if on_reset is not None and on_reset in self._reset_hooks:
                    self._reset_hooks.remove(on_reset)
                if not self._watchers:
                    self._watch_stop.set()
        return unsubscribe

    def _watch_loop(self, stop: threading.Event) -> None:
        try:   # start from "now": a new watcher must not replay history
            since = self._call("GET", "/watch?tail=1")["next"]
        except (ValueError, NotFound, OSError):
            since = 0
        while not stop.is_set():
            try:
                out = self._call(
                    "GET", f"/watch?since={since}&timeout=2",
                    timeout=self.timeout + 5)
            except (ValueError, NotFound, OSError):
                if stop.wait(0.2):
                    break
                continue
            if stop.is_set():
                # our generation was stopped while the poll was in
                # flight: a NEWER generation (with its own fresh tail)
                # may own the watcher list now — delivering this batch
                # would replay pre-subscription events to it, twice
                break
            if out.get("reset"):
                since = out["next"]   # lagged: skip ahead
                with self._watch_lock:
                    hooks = list(self._reset_hooks)
                for h in hooks:       # cache subscribers relist here
                    try:
                        h()
                    except Exception as e:   # a failing relist (e.g.
                        # transient HTTP error) must not kill the shared
                        # poll thread — the next reset retries it
                        log.error("watch_reset_hook", error=str(e))
                continue
            since = out.get("next", since)
            for e in out.get("events", []):
                try:
                    ev = WatchEvent(kind=e["kind"], type=e["type"],
                                    obj=from_doc(e["kind"], e["object"]))
                except (KeyError, ValueError, TypeError) as err:
                    log.error("watch_event_decode", error=str(err))
                    continue
                with self._watch_lock:
                    watchers = list(self._watchers)
                for w in watchers:
                    try:
                        w(ev)
                    except Exception as err:   # one bad subscriber must
                        log.error("watch_callback",  # not starve the rest
                                  error=str(err))
        with self._watch_lock:
            if self._watch_thread is threading.current_thread():
                self._watch_thread = None

    def close(self) -> None:
        with self._watch_lock:
            self._watch_stop.set()
            t = self._watch_thread
        if t is not None:
            t.join(timeout=5)

"""TrainCheckpointer: restore-or-init, sharding-aware restore across a
mesh change, cadence, retention."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubegpu_tpu.ckpt import TrainCheckpointer
from kubegpu_tpu.models import LlamaConfig, llama_init, llama_param_specs
from kubegpu_tpu.parallel import make_mesh, named_sharding_tree


@pytest.fixture
def tiny_state():
    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(1e-3)
    return cfg, params, opt, opt.init(params)


class TestTrainCheckpointer:
    def test_fresh_directory_inits_at_zero(self, tmp_path, tiny_state):
        cfg, params, opt, opt_state = tiny_state
        ck = TrainCheckpointer(str(tmp_path / "ck"))
        state, step = ck.restore_or_init(
            {"params": params, "opt_state": opt_state})
        assert step == 0
        assert state["params"] is params
        ck.close()

    def test_roundtrip_preserves_params_and_opt_state(self, tmp_path,
                                                      tiny_state):
        cfg, params, opt, opt_state = tiny_state
        ck = TrainCheckpointer(str(tmp_path / "ck"))
        # mutate so restore has something to prove
        params2 = jax.tree.map(lambda x: x + 1, params)
        ck.save(4, {"params": params2, "opt_state": opt_state})
        ck.wait()
        ck2 = TrainCheckpointer(str(tmp_path / "ck"))
        state, step = ck2.restore_or_init(
            {"params": params, "opt_state": opt_state})
        assert step == 5
        np.testing.assert_allclose(
            np.asarray(state["params"]["final_norm"]),
            np.asarray(params2["final_norm"]))
        # opt_state structure survives (adamw moments, not reset)
        assert jax.tree.structure(state["opt_state"]) == \
            jax.tree.structure(opt_state)
        ck.close()
        ck2.close()

    def test_sharded_restore_relays_out(self, tmp_path, tiny_state):
        """Restore onto a mesh layout (the rescheduled-gang path)."""
        cfg, params, opt, opt_state = tiny_state
        ck = TrainCheckpointer(str(tmp_path / "ck"))
        ck.save(0, {"params": params, "opt_state": opt_state})
        ck.wait()
        mesh = make_mesh({"dp": 2, "tp": 4})
        specs = named_sharding_tree(mesh, llama_param_specs(cfg))
        state, step = ck.restore_or_init(
            {"params": params, "opt_state": opt_state},
            shardings={"params": specs})
        assert step == 1
        wq = state["params"]["layers"]["wq"]
        assert len(wq.sharding.device_set) > 1   # really laid out
        np.testing.assert_allclose(np.asarray(wq),
                                   np.asarray(params["layers"]["wq"]),
                                   atol=0, rtol=0)
        with pytest.raises(KeyError, match="unknown state keys"):
            ck.restore_or_init({"params": params},
                               shardings={"nope": specs})
        ck.close()

    def test_cadence_and_retention(self, tmp_path, tiny_state):
        cfg, params, opt, opt_state = tiny_state
        ck = TrainCheckpointer(str(tmp_path / "ck"), max_to_keep=2,
                               save_interval_steps=3)
        state = {"params": params, "opt_state": opt_state}
        saved = [s for s in range(9) if ck.maybe_save(s, state)]
        ck.wait()
        assert saved == [2, 5, 8]     # every 3rd step
        assert ck.latest_step == 8
        assert sorted(ck.manager.all_steps()) == [5, 8]  # keep 2
        ck.close()

"""KTP-Audit: static analysis that guards the serving hot path.

Two prongs, one CLI (``python -m kubegpu_tpu.analysis``, or ``make
analyze``); both emit human + JSON reports and exit nonzero on any
unblessed violation — the repo itself must pass clean.

**Prong 1 — jaxpr/HLO auditor** (:mod:`.jaxpr_audit`): lowers every
serving executable (``decode_block``, ``decode_fused``,
``verify_block``/``verify_fused``, ``prefill_wave``,
``prefill_chunk``, ``adopt_wave``, ``activate_slot``) from a
tiny-config engine on representative abstract shapes and walks the
jaxpr to prove:

- **JXA001 — zero host callbacks**: no ``pure_callback`` /
  ``io_callback`` / ``debug_callback`` primitive anywhere in a
  serving executable (one stray ``jax.debug.print`` is a host round
  trip per tick — the exact host-overhead wall PR 8's fused ticks
  paid down).
- **JXA002 — no silent f32 upcasts** in the bf16/int8 attention
  paths: a per-eqn dtype census flags every
  ``convert_element_type`` from bf16/f16/int8 to f32 unless the
  source function is on the explicit accumulator allowlist
  (rmsnorm / rope / logits-at-selection — ``[[jaxpr.upcast]]`` in
  ``blessed_sites.toml``).
- **CEN001 — compile-signature census**: a scripted workload
  (admission wave → chunked prefill → spec ticks → fused K∈{1,4} →
  quarantine replay) drives ``ContinuousBatcher`` end to end while a
  shim records the lowering signature of every dispatch; the distinct
  set must equal the expected set enumerated in
  :func:`.jaxpr_audit.expected_signatures` — any new signature is a
  recompilation hazard, reported with the offending shape diff.

**Prong 2 — AST lint engine** (:mod:`.lint`): repo-specific rules
with stable codes over all of ``kubegpu_tpu/``:

=======  =============================================================
code     rule (rationale / how to bless)
=======  =============================================================
KTP001   ``list.pop(0)`` — O(n) shift per pop on hot paths; use
         ``collections.deque`` (or ``heapq`` for sorted pops).  Bless
         with an inline ``# ktp: allow(KTP001) reason`` pin when the
         list is provably tiny and bounded.
KTP002   implicit host sync in the device-code layers (``models/``,
         ``ops/``, ``parallel/``): ``np.asarray`` / ``np.array`` /
         ``.item()`` / ``jax.device_get`` / ``float|int|bool(jnp…)``.
         Every fetch outside the blessed gates (``_collect``,
         ``_consume_fused``'s input, warmup's compile barrier) is a
         hidden device round trip.  Bless in
         ``blessed_sites.toml`` ``[[bless]]`` with file+func+reason.
KTP003   unseeded RNG / wall-clock read inside a TRACED function
         (jitted, shard_mapped, or scanned): the value is frozen at
         trace time.  Thread keys / timestamps in as arguments.
KTP004   every metric/span name observed in code must appear in the
         ``obs/metrics.py`` METRICS TABLE (the documented-name
         registry, :func:`kubegpu_tpu.obs.metrics.documented_names`).
         "Bless" by adding the missing table row — that IS the fix.
KTP005   unbounded list/dict growth in long-lived engine / pool /
         tracer / registry classes: appended per event with no
         eviction anywhere in the class.  Fix with
         ``deque(maxlen=…)`` or an eviction sweep — passing the
         attribute to a ``*trim*``/``*prune*``/``*evict*``/
         ``*drain*`` helper counts; bless only with a lifetime
         argument (object dies with the request window).
KTP006   attribute written under the class lock in one method but
         bare in another, in a ``threading``-importing module — an
         inconsistently-locked write is a data race.  Methods named
         ``*_locked`` are caller-holds-lock by convention and count
         as locked.  Bless with the single-writer argument if one
         thread provably owns it.
KTP007   serving executable without donation: inside the engine
         factories (``_engine_fns`` / ``_paged_engine_fns``), every
         jit-family wrap of a body that threads a ``pool``/``cache``
         parameter must spell an explicit ``donate=`` — an
         undeclared wrap keeps input AND output pool buffers live,
         silently doubling steady-state KV HBM (ISSUE 10).  Bless
         only with the why-not argument (a genuinely non-aliasable
         layout).
=======  =============================================================

How to bless a site: prefer a ``[[bless]]`` entry in
``analysis/blessed_sites.toml`` (rule + file + func + reason) for
standing architectural gates; use an inline
``# ktp: allow(KTPxxx) reason`` comment pin for one-off sites where
the justification should sit next to the code.  Blessed findings
still appear in the JSON report under ``"blessed"`` so reviews can
audit the allowlist itself.

The README's "Static analysis" section mirrors this table.
"""

from .jaxpr_audit import (audit_engine_executables, compile_census,
                          expected_signatures)
from .lint import RULES, lint_package
from .report import Finding, Report

__all__ = [
    "Finding", "Report", "RULES", "lint_package",
    "audit_engine_executables", "compile_census",
    "expected_signatures", "run_all",
]


def run_all(root=None, census: bool = True) -> Report:
    """Run both prongs; the CLI's single entry point.

    ``root`` defaults to the installed ``kubegpu_tpu`` package dir;
    ``census=False`` skips the compile-signature census (the slowest
    pass — it compiles the tiny engine's executables for real)."""
    import pathlib

    from .blessed import Blessings

    if root is None:
        root = pathlib.Path(__file__).resolve().parent.parent
    root = pathlib.Path(root)
    blessings = Blessings.load()
    report = Report()
    report.extend(lint_package(root, blessings))
    audit_findings, audit_summary = audit_engine_executables(blessings)
    report.extend(audit_findings)
    report.summaries["jaxpr_audit"] = audit_summary
    if census:
        census_findings, census_summary = compile_census()
        report.extend(census_findings)
        report.summaries["compile_census"] = census_summary
    return report

"""Blessed-site allowlist: ``analysis/blessed_sites.toml`` + inline
comment pins.

Two ways to bless a site the linter or jaxpr auditor flags:

1. A TOML entry (reviewed, carries a reason — preferred for standing
   architectural gates like the engine's single host-fetch point)::

       [[bless]]
       rule = "KTP002"
       file = "kubegpu_tpu/models/serve.py"
       func = "ContinuousBatcher._collect"
       reason = "THE host sync — the engine's one designed fetch gate"

   ``func`` matches the qualified name of the ENCLOSING function
   (suffix match, so ``_collect`` also works); omit it to bless a
   whole file for that rule (used sparingly).

2. An inline comment pin on the flagged line (or the line above) —
   for one-off sites where the TOML indirection would hide the
   justification from the reader::

       free.pop(0)   # ktp: allow(KTP001) bounded n_slots scan

Jaxpr-audit upcast allowlisting uses the ``[[jaxpr.upcast]]`` tables:
``func`` is the function name jax's source info attributes the
``convert_element_type`` to (e.g. ``_rmsnorm``).

The loader prefers stdlib ``tomllib`` (3.11+), falls back to ``tomli``,
and finally to a minimal line parser that understands exactly the
subset this file uses — the container must never need a new dep.
"""

from __future__ import annotations

import pathlib
import re


def _parse_toml(text: str) -> dict:
    try:
        import tomllib
        return tomllib.loads(text)
    except ImportError:
        pass
    try:
        import tomli
        return tomli.loads(text)
    except ImportError:
        pass
    return _parse_minimal(text)


def _parse_minimal(text: str) -> dict:
    """Fallback parser for the restricted shape blessed_sites.toml
    uses: ``[[dotted.table]]`` array-of-table headers and
    ``key = "string"`` entries.  No nesting beyond the header path, no
    non-string values — by construction of the file it parses."""
    doc: dict = {}
    current: dict | None = None
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = re.fullmatch(r"\[\[([A-Za-z0-9_.]+)\]\]", line)
        if m:
            node = doc
            parts = m.group(1).split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            current = {}
            node.setdefault(parts[-1], []).append(current)
            continue
        m = re.fullmatch(r'([A-Za-z0-9_]+)\s*=\s*"((?:[^"\\]|\\.)*)"',
                         line)
        if m and current is not None:
            current[m.group(1)] = m.group(2).replace('\\"', '"')
            continue
        raise ValueError(
            f"blessed_sites.toml line not understood by the fallback "
            f"parser (install tomli or simplify the entry): {raw!r}")
    return doc


_DEFAULT_PATH = pathlib.Path(__file__).with_name("blessed_sites.toml")

# inline pin: `# ktp: allow(KTP001) optional reason`
_INLINE_RE = re.compile(r"#\s*ktp:\s*allow\((KTP\d{3}|JXA\d{3})\)\s*(.*)")


class Blessings:
    """Allowlist lookups for both prongs."""

    def __init__(self, doc: dict):
        self._lint = doc.get("bless", []) or []
        jaxpr = doc.get("jaxpr", {}) or {}
        self._upcast = jaxpr.get("upcast", []) or []
        self._callback = jaxpr.get("callback", []) or []

    @classmethod
    def load(cls, path: pathlib.Path | None = None) -> "Blessings":
        p = path or _DEFAULT_PATH
        if not p.exists():
            return cls({})
        return cls(_parse_toml(p.read_text()))

    def lint_reason(self, rule: str, relpath: str,
                    qualname: str) -> str | None:
        """TOML blessing for a lint finding; returns the reason or
        None.  ``qualname`` is the enclosing function's dotted name
        ("" at module level)."""
        rel = relpath.replace("\\", "/")
        for e in self._lint:
            if e.get("rule") != rule:
                continue
            if e.get("file") and not rel.endswith(e["file"]):
                continue
            func = e.get("func")
            if func and not (qualname == func
                             or qualname.endswith("." + func)
                             or qualname.split(".")[-1] == func):
                continue
            if not e.get("file") and not func:
                continue
            return e.get("reason", "blessed")
        return None

    def upcast_reason(self, file: str, func: str) -> str | None:
        """Jaxpr-audit blessing for an intentional f32 upcast,
        matched on the source function jax attributes the convert to."""
        f = file.replace("\\", "/")
        for e in self._upcast:
            if e.get("func") and e["func"] != func:
                continue
            if e.get("file") and not f.endswith(e["file"]):
                continue
            if not e.get("func") and not e.get("file"):
                continue
            return e.get("reason", "blessed")
        return None

    def callback_reason(self, file: str, func: str) -> str | None:
        f = file.replace("\\", "/")
        for e in self._callback:
            if e.get("func") and e["func"] != func:
                continue
            if e.get("file") and not f.endswith(e["file"]):
                continue
            if not e.get("func") and not e.get("file"):
                continue
            return e.get("reason", "blessed")
        return None


def inline_allow(src_lines: list[str], line: int,
                 rule: str) -> str | None:
    """Inline comment pin on the flagged line or the line above.
    ``line`` is 1-indexed."""
    for ln in (line, line - 1):
        if 1 <= ln <= len(src_lines):
            m = _INLINE_RE.search(src_lines[ln - 1])
            if m and m.group(1) == rule:
                return m.group(2).strip() or "inline pin"
    return None

"""Llama serving workload — decode as a SCHEDULABLE job, not just a
library call: the pod runs prefill + greedy decode on its allocated
chip(s) and prints a metric line the node agent harvests into the
cluster registry (like the allreduce bench does for north-star #2).

Env knobs:
  SERVE_BATCH    sequences (default 4)
  SERVE_PROMPT   prompt length (default 128)
  SERVE_STEPS    decode steps (default 32)
  SERVE_INT8     "1" quantizes weights AND KV cache (default 0)
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> int:
    from kubegpu_tpu.workloads.programs.distributed import init_from_env

    env = init_from_env()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubegpu_tpu.models import (
        LlamaConfig, greedy_generate, llama_init, quantize_llama,
    )

    batch = int(os.environ.get("SERVE_BATCH", "4"))
    prompt_t = int(os.environ.get("SERVE_PROMPT", "128"))
    steps = int(os.environ.get("SERVE_STEPS", "32"))
    int8 = os.environ.get("SERVE_INT8", "0") == "1"

    cfg = LlamaConfig.tiny(n_heads=4, n_kv_heads=4, dtype="float32",
                           max_seq_len=prompt_t + steps)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    if int8:
        params = quantize_llama(params)
    prompt = jnp.asarray(
        np.arange(batch * prompt_t).reshape(batch, prompt_t)
        % cfg.vocab_size, jnp.int32)

    out = greedy_generate(params, prompt, steps, cfg,
                          max_len=prompt_t + steps, kv_int8=int8)
    jax.block_until_ready(out)           # warm + compile
    t0 = time.perf_counter()
    out = greedy_generate(params, prompt, steps, cfg,
                          max_len=prompt_t + steps, kv_int8=int8)
    first = int(np.asarray(out)[0, 0])   # host fetch = real barrier
    elapsed = time.perf_counter() - t0

    ok = 0 <= first < cfg.vocab_size
    if env.worker_id == 0:
        # the metric-line convention harvest_workload_metrics consumes
        print(json.dumps({
            "metric": "serve_decode_tokens_per_s",
            "value": round(batch * steps / elapsed, 1),
            "unit": "tokens/s",
            "batch": batch, "prompt": prompt_t, "steps": steps,
            "int8": int8, "devices": jax.device_count(),
        }))
    if not ok:
        print("FAIL: generated token out of range", file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""ctypes bindings for the C++ allocator core (csrc/allocator_core.cpp).

The reference's hot loop was native Go; KubeTPU's is C++ behind a C ABI —
pybind11 isn't available in this environment, so the bindings are plain
ctypes over flat int32/float64 arrays (SURVEY.md §8 step 3).

Loading is lazy and fail-soft: on first use we build the shared library
with the csrc Makefile if it's missing or stale, and if anything goes
wrong (no compiler, exotic platform) every entry point returns ``None`` so
callers fall back to the pure-Python reference implementations.  Set
``KUBETPU_NO_NATIVE=1`` to force the Python path (used by parity tests).
"""

from __future__ import annotations

import array
import ctypes
import itertools
import os
import subprocess
from pathlib import Path

from kubegpu_tpu.topology.mesh import Coord, TpuTopology

_CSRC = Path(__file__).parent / "csrc"
_SO = _CSRC / "libktpu_alloc.so"

_lib: ctypes.CDLL | None = None
_load_failed = False


def _build() -> bool:
    src = _CSRC / "allocator_core.cpp"
    try:
        if _SO.exists() and (
                not src.exists()  # prebuilt .so shipped without source
                or _SO.stat().st_mtime >= src.stat().st_mtime):
            return True
        subprocess.run(
            ["make", "-s"], cwd=_CSRC, check=True,
            capture_output=True, timeout=120)
        return _SO.exists()
    except (OSError, subprocess.SubprocessError):
        return False


def get_lib() -> ctypes.CDLL | None:
    global _lib, _load_failed
    if os.environ.get("KUBETPU_NO_NATIVE"):
        return None
    if _lib is not None:
        return _lib
    if _load_failed:
        return None
    if not _build():
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(str(_SO))
    except OSError:
        _load_failed = True
        return None
    i32 = ctypes.c_int32
    lib.ktpu_find_free_placements.restype = i32
    lib.ktpu_find_free_placements.argtypes = [
        i32, i32, i32, i32, i32, i32,
        ctypes.POINTER(ctypes.c_uint8), i32, i32, i32,
        i32, i32, ctypes.POINTER(i32), ctypes.POINTER(i32)]
    lib.ktpu_eval_order.restype = ctypes.c_double
    lib.ktpu_eval_order.argtypes = [
        i32, i32, i32, i32, i32, i32,
        ctypes.POINTER(i32), i32, ctypes.POINTER(i32),
        ctypes.POINTER(ctypes.c_double), i32]
    lib.ktpu_fragmentation_score.restype = ctypes.c_double
    lib.ktpu_fragmentation_score.argtypes = [
        i32, i32, i32, i32, i32, i32,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(i32), i32]
    lib.ktpu_orient_rings.restype = i32
    lib.ktpu_orient_rings.argtypes = [
        ctypes.POINTER(i32), ctypes.POINTER(i32), ctypes.POINTER(i32),
        i32, i32, ctypes.POINTER(i32)]
    lib.ktpu_align_units.restype = i32
    lib.ktpu_align_units.argtypes = [
        ctypes.POINTER(i32), ctypes.POINTER(i32), i32, i32,
        ctypes.POINTER(i32)]
    lib.ktpu_connected_order.restype = i32
    lib.ktpu_connected_order.argtypes = [
        i32, i32, i32, i32, i32, i32,
        ctypes.POINTER(ctypes.c_uint8), i32, i32, i32,
        i32, i32, i32, ctypes.POINTER(i32)]
    lib.ktpu_rank_free_placements.restype = i32
    lib.ktpu_rank_free_placements.argtypes = [
        i32, i32, i32, i32, i32, i32,
        ctypes.POINTER(ctypes.c_uint8), i32, i32, i32,
        i32, i32, ctypes.POINTER(i32), ctypes.POINTER(i32),
        ctypes.POINTER(ctypes.c_double)]
    _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


# -- marshalling helpers ----------------------------------------------------

def _occupancy_mask(topo: TpuTopology, occupied: set[Coord]) -> ctypes.Array:
    mx, my, mz = topo.spec.mesh_shape
    buf = (ctypes.c_uint8 * (mx * my * mz))()
    for (x, y, z) in occupied:
        if 0 <= x < mx and 0 <= y < my and 0 <= z < mz:
            buf[(x * my + y) * mz + z] = 1
    return buf


def occupancy_mask(topo: TpuTopology, occupied: set[Coord]):
    """Prebuilt occupancy buffer for threading ONE O(chips) mask build
    through a whole per-slice search (shape enumeration + frag ranking
    + connected fallback all take it) — rebuilt per native call it was
    ~30% of a small-gang decision on a 1024-chip cluster.  None when
    the native library is unavailable."""
    if get_lib() is None:
        return None
    return _occupancy_mask(topo, occupied)


def _coords_array(coords) -> ctypes.Array:
    """Coord iterable → int32 buffer.  One array() construction over a
    C-level chain instead of a Python-level extend per coord — the
    per-coord loop was the top tottime line of 256-chip placements
    (~37k extends per find_assignment for the ring-orientation
    marshalling)."""
    flat = array.array("i", itertools.chain.from_iterable(coords))
    return (ctypes.c_int32 * len(flat)).from_buffer(flat)


# -- entry points (None = fall back to Python) ------------------------------

def find_free_placements_native(
    topo: TpuTopology, occupied: set[Coord], shape: Coord,
    limit: int | None, mask=None):
    lib = get_lib()
    if lib is None:
        return None
    mx, my, mz = topo.spec.mesh_shape
    wx, wy, wz = topo.spec.wrap
    sx, sy, sz = shape
    vol = sx * sy * sz
    if vol == 0:
        return []
    # worst-case placements = product of per-axis origin counts
    max_out = 1
    for dim, size, wrap in zip((mx, my, mz), shape, (wx, wy, wz)):
        max_out *= dim if (wrap and dim > 2 and size < dim) else max(
            dim - size + 1, 0)
    if max_out == 0:
        return []
    occ = mask if mask is not None else _occupancy_mask(topo, occupied)
    origins = (ctypes.c_int32 * (max_out * 3))()
    coords = (ctypes.c_int32 * (max_out * vol * 3))()
    n = lib.ktpu_find_free_placements(
        mx, my, mz, int(wx), int(wy), int(wz), occ, sx, sy, sz,
        0 if limit is None else limit, max_out, origins, coords)
    if n < 0:
        return None  # mesh too large for the native key; python fallback
    from kubegpu_tpu.topology.slices import Placement
    out = []
    for i in range(n):
        base = i * vol * 3
        cs = tuple(
            (coords[base + j * 3], coords[base + j * 3 + 1],
             coords[base + j * 3 + 2])
            for j in range(vol))
        out.append(Placement(
            origin=(origins[i * 3], origins[i * 3 + 1], origins[i * 3 + 2]),
            shape=shape, coords=cs))
    return out


def rank_free_placements_native(
    topo: TpuTopology, occupied: set[Coord], shape: Coord,
    limit: int | None, k: int, mask=None):
    """Fused enumerate + frag-rank: returns the top-``k`` free
    placements of ``shape`` as ``[(frag, Placement), ...]`` sorted frag
    descending (ties in enumeration order — byte-identical to the
    Python rank-then-truncate), or None to fall back.  Keeps the
    O(limit × shapes) placement objects out of Python entirely."""
    lib = get_lib()
    if lib is None or k <= 0:
        return None
    mx, my, mz = topo.spec.mesh_shape
    wx, wy, wz = topo.spec.wrap
    sx, sy, sz = shape
    vol = sx * sy * sz
    if vol == 0:
        return []
    occ = mask if mask is not None else _occupancy_mask(topo, occupied)
    origins = (ctypes.c_int32 * (k * 3))()
    coords = (ctypes.c_int32 * (k * vol * 3))()
    frags = (ctypes.c_double * k)()
    n = lib.ktpu_rank_free_placements(
        mx, my, mz, int(wx), int(wy), int(wz), occ, sx, sy, sz,
        0 if limit is None else limit, k, origins, coords, frags)
    if n < 0:
        return None
    from kubegpu_tpu.topology.slices import Placement
    out = []
    for i in range(n):
        base = i * vol * 3
        cs = tuple(
            (coords[base + j * 3], coords[base + j * 3 + 1],
             coords[base + j * 3 + 2])
            for j in range(vol))
        out.append((frags[i], Placement(
            origin=(origins[i * 3], origins[i * 3 + 1],
                    origins[i * 3 + 2]),
            shape=shape, coords=cs)))
    return out


def eval_order_native(
    topo: TpuTopology, order: list[Coord], axes: dict[str, int],
    axis_weights: dict[str, float] | None):
    lib = get_lib()
    if lib is None:
        return None
    # cross-mesh coords (DCN pairs) only arise in multi-slice scoring,
    # which stays on the python path
    for c in order:
        if not topo.has_coord(c):
            return None
    mx, my, mz = topo.spec.mesh_shape
    wx, wy, wz = topo.spec.wrap
    names = list(axes.keys())
    sizes = (ctypes.c_int32 * len(names))(*[axes[k] for k in names])
    w = axis_weights or {}
    weights = (ctypes.c_double * len(names))(
        *[float(w.get(k, 1.0)) for k in names])
    res = lib.ktpu_eval_order(
        mx, my, mz, int(wx), int(wy), int(wz),
        _coords_array(order), len(order), sizes, weights, len(names))
    if res < 0:
        raise ValueError(f"mesh axes {axes} ≠ {len(order)} chips")
    return res


def _flatten_options(options: list[list[list[Coord]]]) -> ctypes.Array:
    return _coords_array(itertools.chain.from_iterable(
        itertools.chain.from_iterable(options)))


def orient_rings_native(options: list[list[list[Coord]]],
                        close: bool) -> list[Coord] | None:
    """Native Viterbi over per-block orientation options (gang.py
    ``_orient_rings``).  ``options[b]`` is block b's orientation list."""
    lib = get_lib()
    if lib is None or not options:
        return None
    n_blocks = len(options)
    n_opts = (ctypes.c_int32 * n_blocks)(*[len(o) for o in options])
    opt_len = (ctypes.c_int32 * n_blocks)(*[len(o[0]) for o in options])
    data = _flatten_options(options)
    choice = (ctypes.c_int32 * n_blocks)()
    rc = lib.ktpu_orient_rings(
        data, n_opts, opt_len, n_blocks, int(close), choice)
    if rc != 0:
        return None
    out: list[Coord] = []
    for b in range(n_blocks):
        out.extend(options[b][choice[b]])
    return out


def align_units_native(options: list[list[list[Coord]]]
                       ) -> list[Coord] | None:
    """Native Viterbi ring alignment (gang.py ``_align_units``):
    ``options[u]`` is unit u's orientation-variant list (all variants the
    same length).  Returns the assembled coord sequence or None to fall
    back to Python."""
    lib = get_lib()
    if lib is None or len(options) < 2:
        return None
    opt_len = len(options[0][0])
    n_units = len(options)
    n_opts = (ctypes.c_int32 * n_units)(*[len(o) for o in options])
    data = _flatten_options(options)
    choice = (ctypes.c_int32 * n_units)()
    rc = lib.ktpu_align_units(data, n_opts, opt_len, n_units, choice)
    if rc != 0:
        return None
    out: list[Coord] = []
    for u in range(n_units):
        out.extend(options[u][choice[u]])
    return out


def connected_order_native(
    topo: TpuTopology, blocked: set[Coord], total: int,
    chips_per_pod: int, num_pods: int, mask=None
) -> tuple[bool, list[Coord] | None] | None:
    """Native connected-region fallback search (gang.py
    ``_connected_candidate``): returns (True, order) with the chunked
    chip order, (False, None) when provably no start works, or None to
    fall back to Python (library unavailable)."""
    lib = get_lib()
    if lib is None:
        return None
    mx, my, mz = topo.spec.mesh_shape
    wx, wy, wz = topo.spec.wrap
    hx, hy, hz = topo.spec.host_block
    occ = mask if mask is not None else _occupancy_mask(topo, blocked)
    out = (ctypes.c_int32 * (total * 3))()
    rc = lib.ktpu_connected_order(
        mx, my, mz, int(wx), int(wy), int(wz), occ, hx, hy, hz,
        total, chips_per_pod, num_pods, out)
    if rc == 1:
        return False, None
    if rc != 0:
        return None
    order = [(out[i * 3], out[i * 3 + 1], out[i * 3 + 2])
             for i in range(total)]
    return True, order


def fragmentation_score_native(
    topo: TpuTopology, occupied: set[Coord], coords: tuple[Coord, ...]):
    scorer = frag_scorer_native(topo, occupied)
    if scorer is None:
        return None
    return scorer(coords)


def frag_scorer_native(topo: TpuTopology, occupied: set[Coord],
                       mask=None):
    """Mask-reusing variant for scoring MANY placements against one
    occupancy set: the O(chips) occupancy-mask build happens once, not
    per placement (the per-shape ranking loop scores every free
    placement — rebuilding the mask there dominated the 1024-chip
    bench's decision time)."""
    lib = get_lib()
    if lib is None:
        return None
    mx, my, mz = topo.spec.mesh_shape
    wx, wy, wz = topo.spec.wrap
    occ = mask if mask is not None else _occupancy_mask(topo, occupied)

    def score(coords) -> float:
        return lib.ktpu_fragmentation_score(
            mx, my, mz, int(wx), int(wy), int(wz), occ,
            _coords_array(coords), len(coords))
    return score

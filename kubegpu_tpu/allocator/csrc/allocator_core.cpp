// KubeTPU native allocator core — the schedule-latency hot loop.
//
// Reference parity: the reference's hot loop was Go
// (grpalloc.PodFitsGroupConstraints, SURVEY.md §3/§4.2); KubeTPU's native
// equivalent is this C++ core behind a C ABI consumed via ctypes
// (kubegpu_tpu/allocator/_native.py).  Semantics are bit-for-bit identical
// to the Python reference implementations in topology/slices.py
// (find_free_placements) and topology/locality.py (+allocator/ordering.py:
// evaluate_order) — tests/test_native.py asserts parity on random cases.
//
// Layout conventions (shared with the Python side):
//   - mesh cells are indexed row-major, z fastest: idx = (x*my + y)*mz + z
//   - coords cross the ABI as flat int32 triples [x0,y0,z0, x1,y1,z1, ...]
//   - occupancy is a uint8 mask over cell indices (1 = blocked)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <unordered_set>
#include <utility>
#include <vector>

namespace {

struct MeshView {
  int mx, my, mz;
  bool wx, wy, wz;

  int dim(int axis) const { return axis == 0 ? mx : (axis == 1 ? my : mz); }
  bool wrap(int axis) const { return axis == 0 ? wx : (axis == 1 ? wy : wz); }
  int cell(int x, int y, int z) const { return (x * my + y) * mz + z; }
  int ncells() const { return mx * my * mz; }

  // Torus manhattan distance honoring wraparound (mesh.py hop_distance):
  // wrap reduces an axis delta only when that axis wraps AND dim > 2.
  int hop(const int32_t* a, const int32_t* b) const {
    int d = 0;
    for (int axis = 0; axis < 3; ++axis) {
      int dm = dim(axis);
      int delta = a[axis] - b[axis];
      if (delta < 0) delta = -delta;
      if (wrap(axis) && dm > 2) {
        int other = dm - delta;
        if (other < delta) delta = other;
      }
      d += delta;
    }
    return d;
  }
};

// 128-bit-ish key for a placement's coord-set, for wrapped-placement dedup
// (slices.py enumerate_placements canonicalizes duplicate coord-sets away).
// Meshes up to 512 cells are covered by 8x64 bits; bigger meshes fall back
// to hashing the sorted cell list.
struct SetKey {
  uint64_t w[8];
  bool operator==(const SetKey& o) const {
    return std::memcmp(w, o.w, sizeof(w)) == 0;
  }
};
struct SetKeyHash {
  size_t operator()(const SetKey& k) const {
    uint64_t h = 1469598103934665603ull;
    for (uint64_t v : k.w) {
      h ^= v;
      h *= 1099511628211ull;
    }
    return (size_t)h;
  }
};

// Shared fragmentation heuristic: fraction of the placement's boundary
// (neighbor slots outside it) that is off-mesh or occupied.  `inplace`
// is caller-provided scratch of m.ncells() bytes, containing the
// placement's membership mask on entry; left CLEARED on exit (so a
// ranking loop can reuse one buffer).  Both ktpu_fragmentation_score
// and the fused ktpu_rank_free_placements call this — the rule must
// exist exactly once.
static double frag_score_masked(const MeshView& m, const uint8_t* occupied,
                                const int32_t* coords, int32_t vol,
                                std::vector<uint8_t>& inplace) {
  for (int i = 0; i < vol; ++i) {
    const int32_t* c = coords + i * 3;
    inplace[m.cell(c[0], c[1], c[2])] = 1;
  }
  int64_t boundary = 0, blocked = 0;
  for (int i = 0; i < vol; ++i) {
    const int32_t* c = coords + i * 3;
    for (int axis = 0; axis < 3; ++axis) {
      const int dm = m.dim(axis);
      for (int delta = -1; delta <= 1; delta += 2) {
        int nc[3] = {c[0], c[1], c[2]};
        nc[axis] += delta;
        if (nc[axis] < 0 || nc[axis] >= dm) {
          if (m.wrap(axis) && dm > 2) {
            nc[axis] = ((nc[axis] % dm) + dm) % dm;
          } else {
            ++boundary;
            ++blocked;  // mesh wall counts as packed-against
            continue;
          }
        }
        const int cell = m.cell(nc[0], nc[1], nc[2]);
        if (inplace[cell]) continue;
        ++boundary;
        if (occupied[cell]) ++blocked;
      }
    }
  }
  for (int i = 0; i < vol; ++i) {
    const int32_t* c = coords + i * 3;
    inplace[m.cell(c[0], c[1], c[2])] = 0;
  }
  return boundary ? (double)blocked / (double)boundary : 1.0;
}

// Shared free-placement enumeration: origin order (ox→oy→oz, wrapped
// axes with dim>2 and size<dim contributing all origins), local
// row-major coords (dx outer, dz inner), SetKey dedup BEFORE the
// occupancy filter, stopping after `limit` free placements.  Both
// ktpu_find_free_placements and the fused ktpu_rank_free_placements
// enumerate through this — the order/dedup/limit rules must exist
// exactly once (they define cross-path parity).  `emit(ox, oy, oz,
// coords)` is called per free placement and returns false to abort.
// Returns 0, or -2 when the mesh exceeds the dedup key width, or -3
// when emit aborted.
template <typename F>
static int32_t for_each_free_placement(const MeshView& m,
                                       const uint8_t* occupied, int32_t sx,
                                       int32_t sy, int32_t sz,
                                       int32_t limit, F&& emit) {
  if (sx > m.mx || sy > m.my || sz > m.mz) return 0;
  if (m.ncells() > 512) return -2;  // key width exceeded

  auto origins = [&](int axis, int size) {
    int dm = m.dim(axis);
    int n = (m.wrap(axis) && dm > 2 && size < dm) ? dm : dm - size + 1;
    return n;
  };

  std::unordered_set<SetKey, SetKeyHash> seen;
  seen.reserve(256);
  const int vol = sx * sy * sz;
  std::vector<int32_t> coords(vol * 3);
  int nfree = 0;
  const int nox = origins(0, sx), noy = origins(1, sy), noz = origins(2, sz);
  for (int ox = 0; ox < nox; ++ox) {
    for (int oy = 0; oy < noy; ++oy) {
      for (int oz = 0; oz < noz; ++oz) {
        SetKey key{};
        bool free_ok = true;
        int k = 0;
        for (int dx = 0; dx < sx; ++dx) {
          int x = ox + dx;
          if (x >= m.mx) x -= m.mx;
          for (int dy = 0; dy < sy; ++dy) {
            int y = oy + dy;
            if (y >= m.my) y -= m.my;
            for (int dz = 0; dz < sz; ++dz) {
              int z = oz + dz;
              if (z >= m.mz) z -= m.mz;
              int c = m.cell(x, y, z);
              key.w[c >> 6] |= (1ull << (c & 63));
              if (occupied[c]) free_ok = false;
              coords[k++] = x;
              coords[k++] = y;
              coords[k++] = z;
            }
          }
        }
        if (!seen.insert(key).second) continue;
        if (!free_ok) continue;
        if (!emit(ox, oy, oz, coords.data())) return -3;
        ++nfree;
        if (limit > 0 && nfree >= limit) return 0;
      }
    }
  }
  return 0;
}

}  // namespace

extern "C" {

// Enumerate free contiguous placements of shape (sx,sy,sz), honoring
// per-axis wraparound, skipping any placement touching an occupied cell,
// stopping after `limit` results (limit<=0 means unlimited).
//
// Origin enumeration order matches slices.py (_axis_origins nesting
// ox→oy→oz; wrapped axes with dim>2 and size<dim contribute all origins),
// and each placement's coords are emitted in local row-major order
// (dx outer, dz inner) — downstream worker ordering relies on this.
//
// out_origins: capacity >= limit*3 ints; out_coords: >= limit*vol*3 ints.
// Returns the number of placements written, or -1 if the caller's buffers
// would overflow (cap = max_out placements).
int32_t ktpu_find_free_placements(
    int32_t mx, int32_t my, int32_t mz, int32_t wx, int32_t wy, int32_t wz,
    const uint8_t* occupied, int32_t sx, int32_t sy, int32_t sz,
    int32_t limit, int32_t max_out, int32_t* out_origins,
    int32_t* out_coords) {
  MeshView m{mx, my, mz, wx != 0, wy != 0, wz != 0};
  const int vol = sx * sy * sz;
  int32_t nout = 0;
  int32_t rc = for_each_free_placement(
      m, occupied, sx, sy, sz, limit,
      [&](int ox, int oy, int oz, const int32_t* coords) {
        if (nout >= max_out) return false;  // caller buffer overflow
        out_origins[nout * 3 + 0] = ox;
        out_origins[nout * 3 + 1] = oy;
        out_origins[nout * 3 + 2] = oz;
        std::memcpy(out_coords + (size_t)nout * vol * 3, coords,
                    sizeof(int32_t) * vol * 3);
        ++nout;
        return true;
      });
  if (rc == -3) return -1;  // emit aborted = buffer overflow
  if (rc < 0) return rc;
  return nout;
}

// Weighted ICI locality of a logical device order under a workload's mesh
// axes — locality.py traffic_pairs_for_mesh_axes + ici_locality fused.
//
// order: n coord triples, logical-device order (last axis varies fastest).
// axis_sizes/axis_weights: n_axes parallel arrays; product(sizes) must be n.
// Every axis of size s contributes ring pairs (k, k+1 mod s) within each
// group varying only along that axis; s==2 contributes one pair per group.
// A pair counts as local iff its torus hop distance is exactly 1 (the
// neighbor relation in mesh.py).  Returns locality in [0,1]; 1.0 when no
// pairs.  Returns -1.0 on size mismatch.
double ktpu_eval_order(int32_t mx, int32_t my, int32_t mz, int32_t wx,
                       int32_t wy, int32_t wz, const int32_t* order,
                       int32_t n, const int32_t* axis_sizes,
                       const double* axis_weights, int32_t n_axes) {
  MeshView m{mx, my, mz, wx != 0, wy != 0, wz != 0};
  int64_t total_chips = 1;
  for (int i = 0; i < n_axes; ++i) total_chips *= axis_sizes[i];
  if (total_chips != n) return -1.0;

  // strides for row-major logical indexing (last axis fastest)
  std::vector<int64_t> strides(n_axes, 1);
  for (int i = n_axes - 2; i >= 0; --i)
    strides[i] = strides[i + 1] * axis_sizes[i + 1];

  double total_w = 0.0, local_w = 0.0;
  for (int ax = 0; ax < n_axes; ++ax) {
    const int s = axis_sizes[ax];
    if (s <= 1) continue;
    const double w = axis_weights[ax];
    const int64_t stride = strides[ax];
    for (int64_t base = 0; base < n; ++base) {
      if ((base / stride) % s != 0) continue;
      const int upto = (s == 2) ? 1 : s;  // 2-ring has one unique pair
      for (int k = 0; k < upto; ++k) {
        const int32_t* a = order + (base + (int64_t)k * stride) * 3;
        const int32_t* b = order + (base + (int64_t)((k + 1) % s) * stride) * 3;
        if (a[0] == b[0] && a[1] == b[1] && a[2] == b[2]) continue;
        total_w += w;
        if (m.hop(a, b) == 1) local_w += w;
      }
    }
  }
  return total_w == 0.0 ? 1.0 : local_w / total_w;
}

// Viterbi orientation chaining (gang.py _orient_rings): choose one
// orientation option per host block so each block's entry chip sits next
// to the previous block's exit chip; with `close`, also optimize the wrap
// transition (last block's exit → first block's entry), trying every
// option of block 0 as the start.  This is the measured hot loop of the
// schedule path (the p50-latency metric's inner kernel).
//
// opts_data: concatenated coord triples of every option of every block,
//   laid out block-major then option-major:
//   block0.opt0, block0.opt1, ..., block1.opt0, ...
// n_opts[b], opt_len[b]: option count / coords-per-option of block b.
// out_choice[b]: chosen option index per block.
// Tie-breaking matches the Python reference exactly: strict improvement,
// starts and options visited in index order.  Returns 0 on success.
int32_t ktpu_orient_rings(const int32_t* opts_data, const int32_t* n_opts,
                          const int32_t* opt_len, int32_t n_blocks,
                          int32_t close, int32_t* out_choice) {
  if (n_blocks <= 0) return -1;
  // per-block offsets into opts_data (in int32 units)
  std::vector<int64_t> block_off(n_blocks);
  int64_t off = 0;
  int max_opts = 0;
  for (int b = 0; b < n_blocks; ++b) {
    block_off[b] = off;
    off += (int64_t)n_opts[b] * opt_len[b] * 3;
    if (n_opts[b] > max_opts) max_opts = n_opts[b];
  }
  auto opt_ptr = [&](int b, int j) {
    return opts_data + block_off[b] + (int64_t)j * opt_len[b] * 3;
  };
  // entry coord of option = first triple; exit coord = last triple
  auto trans = [&](int pb, int pj, int nb, int nj) -> int64_t {
    const int32_t* prev = opt_ptr(pb, pj) + (opt_len[pb] - 1) * 3;  // exit
    const int32_t* nxt = opt_ptr(nb, nj);                           // entry
    int d = 0;
    for (int k = 0; k < 3; ++k) {
      int delta = prev[k] - nxt[k];
      d += delta < 0 ? -delta : delta;
    }
    return d == 1 ? 0 : d;
  };
  if (n_blocks == 1) {
    out_choice[0] = 0;
    return 0;
  }

  const int n_starts = close ? n_opts[0] : 1;
  std::vector<int64_t> cost(max_opts), ncost(max_opts);
  // back[i-1][j] = predecessor option at block i-1 for option j at block i
  std::vector<int32_t> back((size_t)(n_blocks - 1) * max_opts);
  std::vector<int32_t> best_path(n_blocks);
  int64_t best_total = -1;

  for (int start = 0; start < n_starts; ++start) {
    // block 0 is pinned to `start`
    int prev_count = 1;
    cost[0] = 0;
    for (int i = 1; i < n_blocks; ++i) {
      for (int j = 0; j < n_opts[i]; ++j) {
        int64_t bestc = -1;
        int32_t bestj = 0;
        for (int pj = 0; pj < prev_count; ++pj) {
          const int real_pj = (i == 1) ? start : pj;
          int64_t c = cost[pj] + trans(i - 1, real_pj, i, j);
          if (bestc < 0 || c < bestc) {
            bestc = c;
            bestj = pj;
          }
        }
        ncost[j] = bestc;
        back[(size_t)(i - 1) * max_opts + j] = bestj;
      }
      prev_count = n_opts[i];
      std::swap(cost, ncost);
    }
    for (int j = 0; j < n_opts[n_blocks - 1]; ++j) {
      int64_t total = cost[j];
      if (close) total += trans(n_blocks - 1, j, 0, start);
      if (best_total < 0 || total < best_total) {
        best_total = total;
        // backtrack
        int cur = j;
        for (int i = n_blocks - 1; i >= 1; --i) {
          best_path[i] = cur;
          cur = back[(size_t)(i - 1) * max_opts + cur];
        }
        best_path[0] = start;
      }
    }
  }
  for (int b = 0; b < n_blocks; ++b) out_choice[b] = best_path[b];
  return 0;
}

// Packing heuristic (slices.py fragmentation_score): fraction of the
// placement's boundary (neighbor slots outside it) that is off-mesh or
// occupied.  coords: vol triples; occupied mask as above.
double ktpu_fragmentation_score(int32_t mx, int32_t my, int32_t mz,
                                int32_t wx, int32_t wy, int32_t wz,
                                const uint8_t* occupied,
                                const int32_t* coords, int32_t vol) {
  MeshView m{mx, my, mz, wx != 0, wy != 0, wz != 0};
  std::vector<uint8_t> inplace(m.ncells(), 0);
  return frag_score_masked(m, occupied, coords, vol, inplace);
}

// Fused enumerate + fragmentation-rank (gang.py's per-shape candidate
// ranking): enumerate free placements exactly like
// ktpu_find_free_placements (same origin order, same dedup, stopping
// after `limit` free placements), score each with the
// ktpu_fragmentation_score heuristic inline, stable-sort by frag
// descending (ties keep enumeration order, matching Python's stable
// sort), and emit only the top `k`.  This keeps the ~limit×shapes
// placement objects and their per-placement marshalling out of Python —
// the scheduler only ever *scores* the top few per shape.
//
// out buffers sized for k placements.  Returns placements written,
// -1 on buffer overflow (never happens with k-sized buffers), -2 when
// the mesh exceeds the dedup key width (caller falls back to Python).
int32_t ktpu_rank_free_placements(
    int32_t mx, int32_t my, int32_t mz, int32_t wx, int32_t wy, int32_t wz,
    const uint8_t* occupied, int32_t sx, int32_t sy, int32_t sz,
    int32_t limit, int32_t k, int32_t* out_origins, int32_t* out_coords,
    double* out_frag) {
  MeshView m{mx, my, mz, wx != 0, wy != 0, wz != 0};
  const int vol = sx * sy * sz;
  struct Cand {
    double frag;
    std::vector<int32_t> coords;
    int32_t ox, oy, oz;
  };
  std::vector<Cand> cands;
  std::vector<uint8_t> inplace(m.ncells(), 0);
  int32_t rc = for_each_free_placement(
      m, occupied, sx, sy, sz, limit,
      [&](int ox, int oy, int oz, const int32_t* coords) {
        Cand cd;
        cd.frag = frag_score_masked(m, occupied, coords, vol, inplace);
        cd.coords.assign(coords, coords + vol * 3);
        cd.ox = ox;
        cd.oy = oy;
        cd.oz = oz;
        cands.push_back(std::move(cd));
        return true;
      });
  if (rc < 0) return rc;
  std::stable_sort(cands.begin(), cands.end(),
                   [](const Cand& a, const Cand& b) {
                     return a.frag > b.frag;
                   });
  int32_t nout = 0;
  for (const Cand& cd : cands) {
    if (nout >= k) break;
    out_origins[nout * 3 + 0] = cd.ox;
    out_origins[nout * 3 + 1] = cd.oy;
    out_origins[nout * 3 + 2] = cd.oz;
    std::memcpy(out_coords + (size_t)nout * vol * 3, cd.coords.data(),
                sizeof(int32_t) * vol * 3);
    out_frag[nout] = cd.frag;
    ++nout;
  }
  return nout;
}

// Viterbi ring alignment (gang.py _align_units): choose an orientation per
// ring so POSITION-WISE pairs between consecutive rings (and last→first)
// maximize unwrapped-ICI adjacency.  All rings share opt_len; unit 0's
// start is restricted to its first two variants (identity/reversal —
// global rotations preserve all pairwise gains).  Tie-breaking matches the
// Python reference exactly: strict >, first maximum wins, earlier start
// wins, option index order.
int32_t ktpu_align_units(const int32_t* opts_data, const int32_t* n_opts,
                         int32_t opt_len, int32_t n_units,
                         int32_t* out_choice) {
  if (n_units < 2 || opt_len <= 0) return -1;
  std::vector<int64_t> unit_off(n_units);
  int64_t off = 0;
  int max_opts = 0;
  for (int u = 0; u < n_units; ++u) {
    if (n_opts[u] <= 0) return -1;
    unit_off[u] = off;
    off += (int64_t)n_opts[u] * opt_len * 3;
    if (n_opts[u] > max_opts) max_opts = n_opts[u];
  }
  auto opt_ptr = [&](int u, int j) {
    return opts_data + unit_off[u] + (int64_t)j * opt_len * 3;
  };
  // positions i of rings a, b with |Δ| manhattan (no wrap) == 1
  auto gain = [&](const int32_t* a, const int32_t* b) -> int64_t {
    int64_t g = 0;
    for (int i = 0; i < opt_len; ++i) {
      const int32_t* p = a + (int64_t)i * 3;
      const int32_t* q = b + (int64_t)i * 3;
      int d = 0;
      for (int k = 0; k < 3; ++k) {
        int delta = p[k] - q[k];
        d += delta < 0 ? -delta : delta;
      }
      if (d == 1) ++g;
    }
    return g;
  };

  std::vector<int64_t> score(max_opts), nscore(max_opts);
  std::vector<int32_t> back((size_t)(n_units > 2 ? n_units - 2 : 0)
                            * max_opts);
  std::vector<int32_t> best_path(n_units);
  int64_t best_total = -1;
  const int n_starts = n_opts[0] < 2 ? n_opts[0] : 2;

  for (int start = 0; start < n_starts; ++start) {
    const int32_t* s0 = opt_ptr(0, start);
    for (int j = 0; j < n_opts[1]; ++j)
      score[j] = gain(s0, opt_ptr(1, j));
    for (int i = 2; i < n_units; ++i) {
      for (int j = 0; j < n_opts[i]; ++j) {
        int64_t bs = -1;
        int32_t bj = 0;
        for (int pj = 0; pj < n_opts[i - 1]; ++pj) {
          int64_t s = score[pj] + gain(opt_ptr(i - 1, pj), opt_ptr(i, j));
          if (s > bs) {
            bs = s;
            bj = pj;
          }
        }
        nscore[j] = bs;
        back[(size_t)(i - 2) * max_opts + j] = bj;
      }
      std::swap(score, nscore);
    }
    for (int j = 0; j < n_opts[n_units - 1]; ++j) {
      int64_t total = score[j] + gain(opt_ptr(n_units - 1, j), s0);
      if (total > best_total) {
        best_total = total;
        int cur = j;
        for (int i = n_units - 1; i >= 2; --i) {
          best_path[i] = cur;
          cur = back[(size_t)(i - 2) * max_opts + cur];
        }
        best_path[1] = cur;
        best_path[0] = start;
      }
    }
  }
  for (int u = 0; u < n_units; ++u) out_choice[u] = best_path[u];
  return 0;
}

// Connected-region fallback search (gang.py _connected_candidate): from
// each free coord in lexicographic order, grow a connected set of free
// chips with a sorted-frontier BFS (a min-heap keyed on coord — identical
// pop order to the Python heapq frontier), then chunk it
// host-locally (pods take chips_per_pod chips host by host, hosts in id
// order).  Returns 0 + the first start whose chunked order covers `total`
// chips in exactly `num_pods` chunks, 1 when no start works, -1 on bad
// args.  Host ids are row-major (z fastest) over the host-block grid,
// matching TpuTopology.build.
int32_t ktpu_connected_order(int32_t mx, int32_t my, int32_t mz, int32_t wx,
                             int32_t wy, int32_t wz,
                             const uint8_t* blocked, int32_t hx, int32_t hy,
                             int32_t hz, int32_t total,
                             int32_t chips_per_pod, int32_t num_pods,
                             int32_t* out_order) {
  MeshView m{mx, my, mz, wx != 0, wy != 0, wz != 0};
  if (total <= 0 || chips_per_pod <= 0 || hx <= 0 || hy <= 0 || hz <= 0)
    return -1;
  const int n = m.ncells();
  const int hosts_y = (my + hy - 1) / hy, hosts_z = (mz + hz - 1) / hz;
  auto host_of = [&](int x, int y, int z) {
    return ((x / hx) * hosts_y + y / hy) * hosts_z + z / hz;
  };
  // free cells in lexicographic coord order == ascending cell index
  // (cell = (x*my + y)*mz + z is monotone in (x, y, z))
  std::vector<int32_t> free_cells;
  free_cells.reserve(n);
  for (int i = 0; i < n; ++i)
    if (!blocked[i]) free_cells.push_back(i);
  if ((int)free_cells.size() < total) return 1;

  std::vector<uint8_t> seen(n);
  std::vector<int32_t> heap, region, order;
  auto decode = [&](int cell, int32_t* xyz) {
    xyz[2] = cell % mz;
    xyz[1] = (cell / mz) % my;
    xyz[0] = cell / (mz * my);
  };
  for (int32_t start : free_cells) {
    std::fill(seen.begin(), seen.end(), 0);
    heap.clear();
    region.clear();
    seen[start] = 1;
    heap.push_back(start);
    auto cmp = [](int32_t a, int32_t b) { return a > b; };  // min-heap
    while (!heap.empty()) {
      std::pop_heap(heap.begin(), heap.end(), cmp);
      int32_t cur = heap.back();
      heap.pop_back();
      region.push_back(cur);
      if ((int)region.size() >= total) break;
      int32_t c[3];
      decode(cur, c);
      for (int axis = 0; axis < 3; ++axis) {
        const int dim = m.dim(axis);
        if (dim == 1) continue;
        for (int delta = -1; delta <= 1; delta += 2) {
          int32_t nb[3] = {c[0], c[1], c[2]};
          nb[axis] += delta;
          if (nb[axis] < 0 || nb[axis] >= dim) {
            if (!(m.wrap(axis) && dim > 2)) continue;
            nb[axis] = ((nb[axis] % dim) + dim) % dim;
          }
          const int cell = m.cell(nb[0], nb[1], nb[2]);
          if (!seen[cell] && !blocked[cell]) {
            seen[cell] = 1;
            heap.push_back(cell);
            std::push_heap(heap.begin(), heap.end(), cmp);
          }
        }
      }
    }
    if ((int)region.size() < total) continue;
    // group by host id; region cells are in BFS order, so sort each
    // host's chips (cell order == coord order)
    std::vector<std::pair<int32_t, int32_t>> host_cell;  // (host, cell)
    host_cell.reserve(region.size());
    for (int32_t cell : region) {
      int32_t c[3];
      decode(cell, c);
      host_cell.emplace_back(host_of(c[0], c[1], c[2]), cell);
    }
    std::sort(host_cell.begin(), host_cell.end());
    order.clear();
    int chunks_formed = 0;
    for (size_t i = 0; i < host_cell.size() && (int)order.size() < total;) {
      size_t j = i;
      while (j < host_cell.size() && host_cell[j].first == host_cell[i].first)
        ++j;
      const int in_host = (int)(j - i);
      const int usable = (in_host / chips_per_pod) * chips_per_pod;
      int take = total - (int)order.size();
      if (usable < take) take = usable;
      for (int k = 0; k < take; ++k)
        order.push_back(host_cell[i + k].second);
      chunks_formed += take / chips_per_pod;
      i = j;
    }
    if ((int)order.size() != total || chunks_formed != num_pods) continue;
    for (int i = 0; i < total; ++i) decode(order[i], out_order + i * 3);
    return 0;
  }
  return 1;
}

}  // extern "C"
